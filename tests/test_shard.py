"""Sharded tracker control plane tests.

Covers the ISSUE 16 contract (doc/fault_tolerance.md "Sharded
tracker"):

* consistent-hash ring stability: membership changes move ONLY the
  jobs whose arc changed hands (adds pull jobs onto the new shard,
  removals strand only the dead shard's jobs), arcs stay balanced, and
  two parties holding the same snapshot agree on every owner;
* generation-bumped redirects: a registration landing on the wrong
  shard gets the typed ``REJECT_SHARD_MOVED`` reply whose reason
  carries gen/shard/endpoint, and the same submission completes on the
  named owner — one round trip, no directory consult;
* the engine rides redirects end to end: a worker bootstrapped with a
  stale tracker address follows the redirect (or its ``RABIT_DIRECTORY``
  client) to the owning shard, and a redirect loop exhausts the
  ``rabit_shard_retries`` budget as typed :class:`ShardMovedError` —
  never a spin;
* the admission race across a handoff: submissions racing a journal
  replay get the typed ``REJECT_REPLAYING`` backoff reject — never a
  silent close, never a duplicate JobState (6 racing submitters);
* the hierarchical obs fold (per-shard merge → thin global aggregator)
  is bit-for-bit the flat fold on both ``/status`` docs and
  ``/metrics`` pages;
* single-shard wire back-compat BOTH directions: a classic (pre-shard)
  client completes a round against a one-shard fleet, and the default
  job's hello stays byte-identical to the classic layout;
* chaos teeth with deterministic injected↔detected pairing at the new
  control-plane sites (``hello``, ``hb``).
"""
import json
import socket
import struct
import sys
import threading
import time

import pytest

from rabit_tpu.obs import export as obs_export
from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.directory import (DEFAULT_VNODES, Directory,
                                         DirectoryClient, DirectoryServer,
                                         HashRing, ring_from_snapshot)
from rabit_tpu.tracker.shard import ShardServer
from rabit_tpu.tracker.tracker import Tracker

pytestmark = pytest.mark.shard


# ------------------------------------------------------------- helpers
def _hello(addr, cmd, task_id, job=P.DEFAULT_JOB, world=0):
    s = socket.create_connection(addr, timeout=30)
    P.send_hello(s, cmd, task_id, world, job=job)
    return s


def _register(addr, task_id, cmd=P.CMD_START, job=P.DEFAULT_JOB,
              world=0, port=12345):
    s = _hello(addr, cmd, task_id, job=job, world=world)
    P.send_str(s, "127.0.0.1")
    P.send_u32(s, port)
    return s


def _shutdown(addr, task_id, job=P.DEFAULT_JOB):
    _hello(addr, P.CMD_SHUTDOWN, task_id, job=job).close()


def _wait(pred, deadline_sec=10.0):
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _launch(worker, world, env, args=("1000", "3"), obs_dir=None):
    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_BACKOFF_BASE_MS": "10", **env}
    return launch(world, [sys.executable, f"tests/workers/{worker}.py",
                          *args], extra_env=env, obs_dir=obs_dir)


class _FakeSock:
    def __init__(self):
        self.data = b""

    def sendall(self, b):
        self.data += bytes(b)


# ------------------------------------------------------ the hash ring
def test_ring_stability_under_add_and_remove():
    """The handoff-cost contract: growing the fleet moves only the jobs
    the NEW shard now owns (~1/N), removing a shard moves ONLY the dead
    shard's jobs — every other job keeps its owner, which is what makes
    a shard failover a bounded replay instead of a fleet reshuffle."""
    names = [f"job{i}" for i in range(2000)]
    ring3 = HashRing([0, 1, 2])
    before = {n: ring3.owner(n) for n in names}

    ring4 = HashRing([0, 1, 2, 3])
    moved = [n for n in names if ring4.owner(n) != before[n]]
    assert moved, "a new shard must take over some arc"
    # every moved job moved TO the new shard, none reshuffled laterally
    assert all(ring4.owner(n) == 3 for n in moved)
    # and the moved fraction is near the ideal 1/4 (loose 2x bounds)
    assert len(names) / 8 < len(moved) < len(names) / 2

    ring_after_death = HashRing([0, 2])  # shard 1 dies
    for n in names:
        if before[n] != 1:
            assert ring_after_death.owner(n) == before[n], n
        else:
            assert ring_after_death.owner(n) in (0, 2)


def test_ring_arcs_are_balanced():
    """The md5 ring spreads SEQUENTIAL job names (the common tenant0..N
    fleet naming) across shards — the linear-hash failure mode where
    they all pile onto one shard stays dead."""
    ring = HashRing([0, 1, 2])
    owners = [ring.owner(f"tenant{i}") for i in range(3000)]
    for idx in (0, 1, 2):
        share = owners.count(idx) / len(owners)
        assert 0.15 < share < 0.55, f"shard {idx} owns {share:.0%}"


def test_ring_from_snapshot_agrees_with_directory():
    """No ring state ever crosses the wire — a client rebuilding the
    ring from the membership snapshot must agree with the authority on
    every owner (same hashes by construction)."""
    d = Directory()
    d.register(0, "127.0.0.1", 9001)
    d.register(2, "127.0.0.1", 9003)
    d.register(5, "127.0.0.1", 9006)
    snap = d.snapshot()
    assert snap["vnodes"] == DEFAULT_VNODES
    ring = ring_from_snapshot(snap)
    for i in range(500):
        name = f"j{i}"
        owner = d.owner(name)
        assert owner is not None and owner[0] == ring.owner(name)


def test_generation_bumps_only_on_membership_changes():
    """Cached rings stay valid exactly as long as membership does: load
    reports and idempotent re-registers never churn the generation; a
    new shard, a moved endpoint, and a removal each bump it."""
    d = Directory()
    assert d.generation == 0
    d.register(0, "127.0.0.1", 9001)
    assert d.generation == 1
    d.register(0, "127.0.0.1", 9001)       # same endpoint: no churn
    d.poll(0, jobs=3, workers=12)          # load report: no churn
    assert d.generation == 1
    assert d.snapshot()["fleet"] == {"jobs": 3, "workers": 12}
    d.register(1, "127.0.0.1", 9002)       # new member
    assert d.generation == 2
    d.register(0, "127.0.0.1", 9099)       # moved endpoint
    assert d.generation == 3
    assert d.remove(1)
    assert d.generation == 4
    assert not d.remove(1)                 # already gone: no churn
    assert d.generation == 4
    ring = ring_from_snapshot(d.snapshot())
    assert ring.owner("anything") == 0     # lone survivor owns it all


# ---------------------------------------------- typed shard redirects
def _two_shard_fleet(world=1):
    d = Directory()
    shards = [ShardServer(world, shard_index=i, directory=d)
              for i in range(2)]
    for t in shards:
        t.start()
    return d, shards


def _owned_job(d, idx, prefix="redir"):
    for i in range(200):
        name = f"{prefix}{i}"
        owner = d.owner(name)
        if owner is not None and owner[0] == idx:
            return name
    raise AssertionError(f"no job name hashes to shard {idx}")


def test_wrong_shard_redirect_round_trip():
    """A submission landing on the wrong shard gets the typed
    ``REJECT_SHARD_MOVED`` whose reason names the current generation
    and the owner's endpoint — and the SAME submission then completes
    on that endpoint.  One redirect hop, zero directory round trips."""
    d, shards = _two_shard_fleet()
    try:
        name = _owned_job(d, 0)
        wrong = (shards[1].host, shards[1].port)
        right = (shards[0].host, shards[0].port)

        s = _register(wrong, "w0", job=name, world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.RejectReply)
        assert reply.code == P.REJECT_SHARD_MOVED
        parsed = P.parse_shard_moved(reply.reason)
        assert parsed is not None, reply.reason
        gen, owner, host, port = parsed
        assert gen == d.generation
        assert owner == 0 and (host, port) == right

        s = _register(right, "w0", job=name, world=1)
        topo = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(topo, P.TopologyReply) and topo.world == 1
        _shutdown(right, "w0", job=name)
        assert shards[1]._svc_counters[
            "job.admission.rejected.shard_moved"] >= 1
        # the reject left no state on the non-owner (stateless contract)
        with shards[1]._jobs_lock:
            assert name not in shards[1]._jobs
    finally:
        for t in shards:
            t.stop()


def test_sticky_job_survives_membership_growth():
    """A job live on its admitting shard stays there when the ring
    later maps it elsewhere (a new shard joined): sticky admission —
    a mid-life membership change never strands a running job."""
    d = Directory()
    sh = ShardServer(1, shard_index=0, directory=d)
    sh.start()
    try:
        addr = (sh.host, sh.port)
        s = _register(addr, "w0", job="stick0", world=1)
        assert P.TopologyReply.recv_or_reject(s).world == 1
        s.close()
        # grow the fleet until some registered name would move — the
        # live job must still be served by shard 0 regardless
        d.register(1, "127.0.0.1", 9, 0)
        s = _register(addr, "w0", cmd=P.CMD_RECOVER, job="stick0",
                      world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.TopologyReply), reply
        _shutdown(addr, "w0", job="stick0")
    finally:
        sh.stop()


# ------------------------------------------- engine-side shard failover
def test_engine_follows_redirect_from_stale_address(tmp_path):
    """A worker bootstrapped with a STALE tracker address (the job's
    previous owner) and a ``rabit_directory`` must land on the owning
    shard: typed redirect → re-target → topology, all inside init()."""
    from rabit_tpu.engine.pysocket import PySocketEngine

    d = Directory()
    server = DirectoryServer(d).start()
    shards = []
    try:
        shards = [ShardServer(1, shard_index=i,
                              directory=f"http://{server.host}:"
                                        f"{server.port}")
                  for i in range(2)]
        for t in shards:
            t.start()
        assert _wait(lambda: len(d.snapshot()["shards"]) == 2)
        name = _owned_job(d, 0, prefix="eng")
        eng = PySocketEngine()
        eng.init({"rabit_tracker_uri": shards[1].host,   # the WRONG one
                  "rabit_tracker_port": shards[1].port,
                  "rabit_task_id": "0", "rabit_world_size": 1,
                  "rabit_job_id": name,
                  "rabit_directory": f"{server.host}:{server.port}",
                  "rabit_backoff_base_ms": 10})
        try:
            assert eng._tracker_addr == (shards[0].host, shards[0].port)
        finally:
            eng.shutdown()
    finally:
        for t in shards:
            t.stop()
        server.stop()


def test_redirect_loop_exhausts_typed_shard_moved_error():
    """A control plane whose redirects never land (two shards pointing
    at each other — a pathological split) must exhaust the
    ``rabit_shard_retries`` budget as a typed :class:`ShardMovedError`
    carrying the last generation/shard — bounded, never a spin."""
    import rabit_tpu
    from rabit_tpu.engine.pysocket import (LinkError, PySocketEngine,
                                           ShardMovedError)

    assert issubclass(ShardMovedError, LinkError)
    assert "ShardMovedError" in rabit_tpu.__all__

    ln = socket.socket()
    ln.bind(("127.0.0.1", 0))
    ln.listen(8)
    host, port = ln.getsockname()
    stop = threading.Event()

    def redirect_forever():
        # a tracker that always answers "the owner is... me": the
        # client's per-redirect re-target can never converge
        while not stop.is_set():
            try:
                s, _ = ln.accept()
            except OSError:
                return
            try:
                P.recv_hello(s)
                P.recv_str(s)          # advertised host
                P.recv_u32(s)          # advertised port
                P.RejectReply(
                    P.REJECT_SHARD_MOVED,
                    P.shard_moved_reason(7, 1, host, port)).send(s)
            except OSError:
                pass
            finally:
                s.close()

    t = threading.Thread(target=redirect_forever, daemon=True)
    t.start()
    eng = PySocketEngine()
    t0 = time.monotonic()
    try:
        with pytest.raises(ShardMovedError) as ei:
            eng.init({"rabit_tracker_uri": host,
                      "rabit_tracker_port": port,
                      "rabit_task_id": "0", "rabit_world_size": 1,
                      "rabit_job_id": "looped",
                      "rabit_shard_retries": 2,
                      "rabit_backoff_base_ms": 5})
        assert time.monotonic() - t0 < 30      # budgeted, not a hang
        assert ei.value.generation == 7 and ei.value.shard == 1
    finally:
        stop.set()
        ln.close()


# ------------------------------------- the admission race across handoff
def test_replay_gate_rejects_racing_submitters_typed(tmp_path):
    """The handoff race (6 racing submitters): submissions landing
    while the shard replays adopted journals get the typed
    ``REJECT_REPLAYING`` — never a silent close — and every one of
    them is admitted once the replay gate drops, with exactly one
    JobState per job (the duplicate-JobState bug stays dead)."""
    d = Directory()
    sh = ShardServer(1, shard_index=0, directory=d,
                     state_dir=str(tmp_path))
    sh.start()
    sh._replay_gate.set()          # hold the gate as a live replay would
    n = 6
    rejects = [0] * n
    errors: list[str] = []

    def submitter(i: int) -> None:
        addr = (sh.host, sh.port)
        job = f"race{i}"
        try:
            for attempt in range(200):
                s = _register(addr, f"w{i}", job=job, world=1)
                reply = P.TopologyReply.recv_or_reject(s)
                s.close()
                if isinstance(reply, P.RejectReply):
                    assert reply.code == P.REJECT_REPLAYING, reply
                    assert "replaying" in reply.reason
                    rejects[i] += 1
                    time.sleep(0.02 * (1 + (attempt % 4)))  # backoff
                    continue
                assert reply.world == 1
                _shutdown(addr, f"w{i}", job=job)
                return
            errors.append(f"submitter {i} never admitted")
        except Exception as e:  # noqa: BLE001 — surfaced as a failure
            errors.append(f"submitter {i}: {type(e).__name__}: {e}")

    try:
        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(n)]
        for th in threads:
            th.start()
        time.sleep(0.3)            # let the race hit the armed gate
        sh._replay_gate.clear()    # replay done
        for th in threads:
            th.join(timeout=60)
        assert not errors, errors
        assert sum(rejects) >= n, rejects  # the gate actually gated
        assert sh._svc_counters["job.admission.rejected.replaying"] >= n
        # one JobState per job, all finished — nothing duplicated,
        # nothing leaked by the rejected attempts
        with sh._jobs_lock:
            names = [k for k in sh._jobs if k.startswith("race")]
        assert sorted(names) == sorted(f"race{i}" for i in range(n))
        assert _wait(lambda: sh._svc_counters.get("job.finished", 0)
                     >= n, 20)
    finally:
        sh.stop()


# -------------------------------------------- the hierarchical obs fold
def _status_doc(shard, ts, jobs, counters, jobs_active):
    return {"ts": ts, "elastic": False, "shard": shard,
            "service": {"jobs_active": list(jobs_active),
                        "counters": dict(counters)},
            "jobs": jobs}


def test_hierarchical_status_fold_equals_flat():
    """Folding per-shard /status docs through an intermediate merge and
    then the global aggregator is bit-for-bit the one-shot flat fold:
    job tables union disjointly (with shard attribution), service
    counters sum, ``jobs_active`` unions sorted — associative by
    construction, so the fleet can nest aggregators freely."""
    d0 = _status_doc(0, 10.0, {"ja": {"world": 2, "done": False}},
                     {"job.created": 1, "scrapes": 4}, ["ja"])
    d1 = _status_doc(1, 12.5, {"jb": {"world": 4, "done": False},
                               "jc": {"world": 1, "done": True}},
                     {"job.created": 2, "job.finished": 1}, ["jb"])
    d2 = _status_doc(2, 11.0, {"jd": {"world": 8, "done": False}},
                     {"job.created": 1}, ["jd"])

    flat = obs_export.merge_status_docs([d0, d1, d2])
    hier = obs_export.merge_status_docs(
        [obs_export.merge_status_docs([d0, d1]),
         obs_export.merge_status_docs([d2])])
    assert json.dumps(hier, sort_keys=True) == \
        json.dumps(flat, sort_keys=True)
    # and the fold did what the docs claim: disjoint union + sums +
    # per-job shard attribution
    assert set(flat["jobs"]) == {"ja", "jb", "jc", "jd"}
    assert flat["jobs"]["jb"]["shard"] == 1
    assert flat["service"]["counters"]["job.created"] == 4
    assert flat["service"]["jobs_active"] == ["ja", "jb", "jd"]
    assert flat["ts"] == 12.5
    # a failed scrape degrades the fold, never poisons it
    degraded = obs_export.merge_status_docs([d0, None, d2])
    assert set(degraded["jobs"]) == {"ja", "jd"}


def test_hierarchical_metrics_fold_equals_flat():
    """Same associativity on the Prometheus pages: per-job series are
    disjoint (labels carry the job) and pass through verbatim; the
    colliding fleet-level series sum — two-level fold == flat fold."""
    p0 = obs_export.prometheus_text(
        [("rabit_job_workers", {"job": "ja"}, 2),
         ("rabit_service_jobs", {}, 1)],
        {"rabit_service_jobs": "gauge"})
    p1 = obs_export.prometheus_text(
        [("rabit_job_workers", {"job": "jb"}, 4),
         ("rabit_service_jobs", {}, 2)],
        {"rabit_service_jobs": "gauge"})
    p2 = obs_export.prometheus_text(
        [("rabit_job_workers", {"job": "jc"}, 8),
         ("rabit_service_jobs", {}, 1)],
        {"rabit_service_jobs": "gauge"})

    flat = obs_export.merge_prometheus_pages([p0, p1, p2])
    hier = obs_export.merge_prometheus_pages(
        [obs_export.merge_prometheus_pages([p0, p1]), p2])
    assert hier == flat
    assert 'rabit_job_workers{job="jb"} 4' in flat
    assert "rabit_service_jobs 4" in flat        # 1 + 2 + 1, summed


# ------------------------------------------- single-shard back-compat
def test_classic_client_completes_round_on_one_shard_fleet():
    """Back-compat direction 2: a pre-shard client (classic MAGIC, no
    job field, hand-written bytes) completes a world-2 round against a
    one-shard fleet — the sharded control plane degrades to the exact
    legacy wire when the fleet is one shard and the job is default."""
    d = Directory()
    sh = ShardServer(2, shard_index=0, directory=d)
    sh.start()
    try:
        socks = []
        for tid in ("0", "1"):
            s = socket.create_connection((sh.host, sh.port), timeout=10)
            # the classic pre-multi-tenant layout, byte by byte
            s.sendall(struct.pack("<I", P.MAGIC))
            for field in (P.CMD_START, tid):
                raw = field.encode()
                s.sendall(struct.pack("<I", len(raw)) + raw)
            s.sendall(struct.pack("<I", 2))       # world hint
            raw = b"127.0.0.1"
            s.sendall(struct.pack("<I", len(raw)) + raw)
            s.sendall(struct.pack("<I", 12345))   # data port
            socks.append(s)
        topos = [P.TopologyReply.recv(s) for s in socks]
        for s in socks:
            s.close()
        assert {t.rank for t in topos} == {0, 1}
        assert all(t.world == 2 for t in topos)
        for tid in ("0", "1"):
            _shutdown((sh.host, sh.port), tid)
    finally:
        sh.stop()


def test_default_job_hello_bytes_unchanged_and_named_on_plain_tracker():
    """Back-compat direction 1: the sharded worker's default-job hello
    is still the classic byte stream (an old tracker cannot tell), and
    a shard-aware worker speaking a NAMED job to a plain (unsharded)
    Tracker just works — no directory required on either side."""
    new = _FakeSock()
    P.send_hello(new, P.CMD_START, "t3", 2)
    old = _FakeSock()
    old.sendall(struct.pack("<I", P.MAGIC))
    for s in (P.CMD_START, "t3"):
        raw = s.encode()
        old.sendall(struct.pack("<I", len(raw)) + raw)
    old.sendall(struct.pack("<I", 2))
    assert new.data == old.data

    t = Tracker(1)
    t.start()
    try:
        addr = (t.host, t.port)
        s = _register(addr, "n0", job="namedjob", world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.TopologyReply) and reply.world == 1
        _shutdown(addr, "n0", job="namedjob")
    finally:
        t.stop()


# -------------------------------------- chaos teeth: control-plane sites
def test_chaos_hello_resets_pair_with_register_retries(tmp_path):
    """Deterministic injected↔detected pairing at the ``hello`` site:
    every injected registration reset MUST surface as exactly one
    ``net.tracker.register_retries`` walk (same per-rank statistics) —
    an injection the detector missed, or a detection nothing injected,
    both fail this gate."""
    assert _launch("check_basic", 2,
                   {"RABIT_ENGINE": "pysocket",
                    "RABIT_CHAOS": "31:reset@hello=1.0*2",
                    "RABIT_CONNECT_RETRIES": "6"},
                   args=("2000",), obs_dir=str(tmp_path)) == 0
    rep = json.loads((tmp_path / "obs_report.json").read_text())
    agg = rep["aggregate"]
    assert agg["chaos.injected.reset"]["max"] >= 1, "vacuous run"
    assert agg["chaos.injected.reset"] == \
        agg["net.tracker.register_retries"]


def test_chaos_hb_resets_pair_with_hb_drops(tmp_path):
    """Same pairing at the ``hb`` site: each injected heartbeat reset
    drops the channel exactly once (``hb.drops``), and the re-dial next
    period keeps the job alive — completion, bit-exact math (the worker
    asserts it), and matched per-rank injected/detected statistics."""
    assert _launch("model_recover", 2,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_CHAOS": "37:reset@hb=1.0*3",
                    "RABIT_HEARTBEAT_SEC": "0.05"},
                   args=("1000", "6"), obs_dir=str(tmp_path)) == 0
    rep = json.loads((tmp_path / "obs_report.json").read_text())
    agg = rep["aggregate"]
    assert agg.get("chaos.injected.reset", {}).get("max", 0) >= 1, \
        "vacuous run — no heartbeat wake consulted the plan"
    assert agg["chaos.injected.reset"] == agg["hb.drops"]


# ----------------------------------------------------- the slow gate
@pytest.mark.slow
def test_soak_shards():
    """The headline failover gate: 6 tenant jobs hash across a 3-shard
    fleet behind a directory; one shard is SIGKILLed mid-training, its
    jobs journal-replay onto survivors at the next generation, every
    final is bit-exact vs a solo run, co-tenants never stall, and the
    fleet-wide books balance (see tools/soak.py --shards)."""
    from rabit_tpu.tools import soak

    rc = soak.main(["--shards", "3", "--tenants", "6", "--rounds", "1",
                    "--seed", "11", "--ndata", "2000", "--niter", "8"])
    assert rc == 0, "shard soak failed — scenario printed above"
