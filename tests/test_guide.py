"""The published C++ tutorial must stay buildable and runnable.

Drives guide/Makefile against the session-built librabit_tpu.so and runs
each tutorial binary as a real multi-worker job through the local
launcher — a header change that breaks the tutorial now fails CI
(reference analogue: guide/Makefile + guide/basic.cc run via
tracker/rabit_demo.py).
"""
import pathlib
import subprocess

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
GUIDE = ROOT / "guide"


@pytest.fixture(scope="module")
def guide_binaries(native_lib):
    """Build all guide/*.cc against the freshly built native lib."""
    proc = subprocess.run(["make", "-C", str(GUIDE), "-B"],
                          capture_output=True, text=True)
    assert proc.returncode == 0, \
        f"guide build failed:\n{proc.stdout}\n{proc.stderr}"
    return GUIDE


@pytest.mark.parametrize("prog,needle", [
    ("basic_cc", "after-allreduce-sum"),
    ("broadcast_cc", None),
    ("lazy_allreduce_cc", None),
])
def test_guide_cc_runs_world3(guide_binaries, prog, needle, capfd):
    """Each tutorial binary completes at world 3 over the native engine
    (reference: guide/basic.cc under tracker/rabit_demo.py -n 3)."""
    from rabit_tpu.tracker.launch_local import launch

    exe = guide_binaries / prog
    assert exe.exists(), f"{prog} was not built"
    code = launch(3, [str(exe), "rabit_engine=native"])
    assert code == 0
    if needle is not None:
        out = capfd.readouterr()
        assert needle in out.out + out.err
