"""Pod launcher test: local submission path (ssh path shares the same
tracker/env wiring, differing only in process transport)."""
import sys


def test_launch_pod_local(native_lib):
    from rabit_tpu.tracker.launch_pod import launch_pod

    code = launch_pod(
        [sys.executable, "guide/basic.py"], n_local=3)
    assert code == 0


def test_launch_pod_watchdog_recovers_stalled_worker(tmp_path, native_lib):
    """A SIGSTOP'd pod worker recovers in seconds: the tracker's stall
    watchdog reports the silent rank, the pod launcher kills+restarts
    it (the launch_local contract, now wired here too), and the job
    finishes with verified numerics."""
    import os
    import time

    from rabit_tpu.tracker.launch_pod import launch_pod

    env = {"RABIT_ENGINE": "native", "RABIT_TIMEOUT_SEC": "6",
           "RABIT_STALL_DIR": str(tmp_path)}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)  # local pod workers inherit os.environ
    try:
        t0 = time.monotonic()
        # watchdog 6s: long enough that a fresh interpreter can start
        # and register within one grace period on a loaded 1-core CI box
        code = launch_pod(
            [sys.executable, "tests/workers/stall_worker.py", "1000", "3"],
            n_local=3, watchdog_sec=6)
        took = time.monotonic() - t0
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
    assert code == 0
    assert took < 120, f"stalled worker took {took:.0f}s to recover"
    assert (tmp_path / "stalled").exists()


def test_hostfile_parsing(tmp_path):
    from rabit_tpu.tracker.launch_pod import _read_hostfile

    f = tmp_path / "hosts"
    f.write_text("# tpu slice\nhost-a slots=8\nhost-b\n\nhost-c\n")
    assert _read_hostfile(str(f)) == ["host-a", "host-b", "host-c"]
