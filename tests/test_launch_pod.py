"""Pod launcher test: local submission path (ssh path shares the same
tracker/env wiring, differing only in process transport)."""
import sys


def test_launch_pod_local(native_lib):
    from rabit_tpu.tracker.launch_pod import launch_pod

    code = launch_pod(
        [sys.executable, "guide/basic.py"], n_local=3)
    assert code == 0


def test_launch_pod_watchdog_recovers_stalled_worker(tmp_path, native_lib):
    """A SIGSTOP'd pod worker recovers in seconds: the tracker's stall
    watchdog reports the silent rank, the pod launcher kills+restarts
    it (the launch_local contract, now wired here too), and the job
    finishes with verified numerics."""
    import os
    import time

    from rabit_tpu.tracker.launch_pod import launch_pod

    env = {"RABIT_ENGINE": "native", "RABIT_TIMEOUT_SEC": "6",
           "RABIT_STALL_DIR": str(tmp_path)}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)  # local pod workers inherit os.environ
    try:
        t0 = time.monotonic()
        # watchdog 6s: long enough that a fresh interpreter can start
        # and register within one grace period on a loaded 1-core CI box
        code = launch_pod(
            [sys.executable, "tests/workers/stall_worker.py", "1000", "3"],
            n_local=3, watchdog_sec=6)
        took = time.monotonic() - t0
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
    assert code == 0
    assert took < 120, f"stalled worker took {took:.0f}s to recover"
    assert (tmp_path / "stalled").exists()


def test_hostfile_parsing(tmp_path):
    from rabit_tpu.tracker.launch_pod import _read_hostfile

    f = tmp_path / "hosts"
    f.write_text("# tpu slice\nhost-a slots=8\nhost-b\n\nhost-c\n")
    assert _read_hostfile(str(f)) == ["host-a", "host-b", "host-c"]


def test_launch_pod_fake_ssh_remote_leg(tmp_path, native_lib):
    """The ssh leg end-to-end without a cluster: a PATH-shimmed ``ssh``
    execs its command locally, so the remote spawn (cwd mirroring, env
    prefixing, ``setsid`` detachment, pidfile write) and the watchdog's
    remote process-group kill all execute for real.  The detachment is
    faithful: ``setsid`` puts the worker in its own session, so killing
    the local "ssh client" Popen alone cannot stop it — the SIGSTOP'd
    rank only dies if the pidfile group kill goes through the ssh
    transport, which is exactly the code under test."""
    import glob
    import os
    import time

    from rabit_tpu.tracker.launch_pod import launch_pod

    fake = tmp_path / "bin" / "ssh"
    fake.parent.mkdir()
    fake.write_text('#!/bin/sh\n'
                    '# fake ssh: <host> <command...> -> run locally\n'
                    'shift\n'
                    'exec sh -c "$*"\n')
    fake.chmod(0o755)
    env = {"RABIT_ENGINE": "native", "RABIT_TIMEOUT_SEC": "6",
           "RABIT_STALL_DIR": str(tmp_path),
           "PATH": str(fake.parent) + os.pathsep + os.environ["PATH"]}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        t0 = time.monotonic()
        code = launch_pod(
            [sys.executable, "tests/workers/stall_worker.py", "500", "3"],
            hosts=["podhost-a", "podhost-b", "podhost-c"],
            tracker_host="127.0.0.1", watchdog_sec=6,
            pidfile_dir=str(tmp_path))
        took = time.monotonic() - t0
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
    assert code == 0
    assert took < 120, f"stalled remote worker took {took:.0f}s to recover"
    assert (tmp_path / "stalled").exists()
    # the remote leg wrote pidfiles for every worker it spawned
    # (scoped to this run's directory so stale files can't satisfy it)
    assert len(glob.glob(str(tmp_path / "rabit_pod_*_*.pid"))) >= 3
