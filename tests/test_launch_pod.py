"""Pod launcher test: local submission path (ssh path shares the same
tracker/env wiring, differing only in process transport)."""
import sys


def test_launch_pod_local(native_lib):
    from rabit_tpu.tracker.launch_pod import launch_pod

    code = launch_pod(
        [sys.executable, "guide/basic.py"], n_local=3)
    assert code == 0


def test_hostfile_parsing(tmp_path):
    from rabit_tpu.tracker.launch_pod import _read_hostfile

    f = tmp_path / "hosts"
    f.write_text("# tpu slice\nhost-a slots=8\nhost-b\n\nhost-c\n")
    assert _read_hostfile(str(f)) == ["host-a", "host-b", "host-c"]
