"""Telemetry subsystem tests (rabit_tpu.obs + tracker aggregation).

Fast unit coverage for the metrics registry (counters / gauges /
log2-bucket histograms), the bounded event trace (eviction, JSONL and
Chrome-trace round trips), the structured logger gating, and the
Timer-over-Histogram fold — plus distributed gates: a 4-rank fixed-op
job must report identical op counts and byte totals on every rank
(pysocket and pyrobust), and a soak round with an injected kill must
produce a tracker-aggregated report with per-op latency percentiles and
the documented recovery timeline, renderable by tools/obs_report.py.
"""
import json
import math
import sys
import threading

import numpy as np
import pytest

from rabit_tpu import obs

pytestmark = pytest.mark.obs


# ---------------------------------------------------------------- metrics
def test_counter_and_gauge():
    m = obs.Metrics()
    m.counter("c").inc()
    m.counter("c").inc(4)
    m.gauge("g").set(2.5)
    snap = m.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 2.5


def test_counter_thread_safety():
    m = obs.Metrics()

    def work():
        for _ in range(10000):
            m.counter("n").inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.counter("n").value == 80000


def test_histogram_welford_matches_numpy():
    h = obs.Histogram()
    rng = np.random.default_rng(0)
    vals = rng.uniform(1e-5, 1e-1, 500)
    for v in vals:
        h.observe(float(v))
    assert h.count == 500
    assert h.mean == pytest.approx(vals.mean(), rel=1e-12)
    assert h.std == pytest.approx(vals.std(), rel=1e-9)
    assert h.max == vals.max()
    assert h.min == vals.min()


def test_histogram_log2_buckets_and_percentiles():
    h = obs.Histogram()
    # one value per octave: percentile estimates must stay within one
    # bucket (factor of 2) of the true order statistics
    for e in range(-10, 0):
        h.observe(1.5 * 2.0 ** e)
    snap = h.snapshot()
    assert sum(snap["buckets"].values()) == 10
    assert len(snap["buckets"]) == 10  # one bucket per octave
    p50 = h.percentile(50)
    true_p50 = 1.5 * 2.0 ** -6
    assert true_p50 / 2 <= p50 <= true_p50 * 2
    assert h.percentile(100) == h.max
    # percentiles never escape the observed range
    assert h.min <= h.percentile(1) <= h.max


def test_histogram_empty():
    h = obs.Histogram()
    assert h.mean == 0.0 and h.std == 0.0 and h.percentile(99) == 0.0
    snap = h.snapshot()
    assert snap["count"] == 0 and snap["min"] == 0.0 and snap["max"] == 0.0


def test_flatten_and_aggregate():
    a, b = obs.Metrics(), obs.Metrics()
    a.counter("op.x.count").inc(3)
    b.counter("op.x.count").inc(5)
    a.histogram("lat").observe(0.5)
    b.histogram("lat").observe(1.5)
    agg = obs.aggregate_snapshots([a.snapshot(), b.snapshot()])
    assert agg["op.x.count"] == {"min": 3.0, "mean": 4.0, "max": 5.0}
    assert agg["lat.mean"]["min"] == 0.5
    assert agg["lat.mean"]["max"] == 1.5


# ------------------------------------------------------------ event trace
def test_ring_buffer_eviction():
    tr = obs.EventTrace(capacity=8)
    for i in range(20):
        tr.emit("op", seqno=i)
    assert len(tr) == 8 and tr.capacity == 8
    assert [e["seqno"] for e in tr.events()] == list(range(12, 20))


def test_trace_jsonl_round_trip():
    tr = obs.EventTrace()
    tr.emit("op", kind="allreduce", nbytes=4096, seqno=1, version=2,
            dur=0.001)
    tr.emit("recovery", phase="link_error", rank=3)
    lines = tr.to_jsonl().splitlines()
    parsed = [json.loads(ln) for ln in lines]
    assert parsed == tr.events()
    assert parsed[0]["kind"] == "allreduce" and parsed[0]["nbytes"] == 4096
    # dur-carrying events are stamped at their START
    assert parsed[0]["ts"] <= parsed[1]["ts"]
    # None-valued fields are dropped, not serialized
    tr2 = obs.EventTrace()
    tr2.emit("op", kind=None, seqno=0)
    assert "kind" not in tr2.events()[0]


def test_chrome_trace_format():
    tr = obs.EventTrace()
    tr.emit("op", kind="allreduce", nbytes=8, dur=0.002, rank=1)
    tr.emit("recovery", phase="rendezvous", rank=0)
    entries = obs.chrome_trace(tr.events())
    spans = [e for e in entries if e["ph"] == "X"]
    instants = [e for e in entries if e["ph"] == "i"]
    assert len(spans) == 1 and len(instants) == 1
    assert spans[0]["dur"] == pytest.approx(2000.0)  # microseconds
    assert spans[0]["pid"] == 1 and instants[0]["pid"] == 0
    assert all(e["ts"] >= 0 for e in entries)


# ---------------------------------------------------------------- logging
def test_logger_debug_gated(capsys):
    log = obs.log.Logger("test", lambda: {"rank": 7})
    obs.log.set_debug(False)
    log.debug("hidden %d", 1)
    log.info("shown %d", 2)
    err = capsys.readouterr().err
    assert "hidden" not in err
    assert "[rabit][test] [rank=7] [INFO] shown 2" in err
    try:
        obs.log.set_debug(True)
        log.debug("now visible")
        assert "now visible" in capsys.readouterr().err
    finally:
        obs.log.set_debug(False)


def test_obs_configure_defaults(monkeypatch):
    monkeypatch.delenv("RABIT_OBS", raising=False)
    monkeypatch.delenv("RABIT_OBS_DIR", raising=False)
    cfg = obs.configure({})
    assert not cfg.enabled and cfg.obs_dir is None
    assert obs.configure({"rabit_obs": "1"}).enabled
    assert not obs.configure({"rabit_obs": "off"}).enabled
    assert obs.configure({"rabit_obs_events": 0}).trace_capacity == 0
    cfg = obs.configure({"rabit_obs_dir": "/tmp/x", "rabit_obs_events": 16})
    assert cfg.enabled and cfg.obs_dir == "/tmp/x"
    assert cfg.trace_capacity == 16


# -------------------------------------------------------- Timer fold-in
def test_timer_welford_std_max():
    from rabit_tpu.utils.profiler import Timer

    t = Timer()
    # drive the shared Histogram directly: Timer must expose its
    # aggregation, not a parallel implementation
    for v in (0.1, 0.2, 0.3):
        t.histogram.observe(v)
    assert t.count == 3
    assert t.total == pytest.approx(0.6)
    assert t.mean == pytest.approx(0.2)
    assert t.std == pytest.approx(math.sqrt(np.var([0.1, 0.2, 0.3])))
    assert t.max == pytest.approx(0.3)
    with t:
        pass
    assert t.count == 4


def test_engine_stats_default_empty(empty_engine):
    from rabit_tpu import engine as _em

    eng = _em.get_engine()
    assert eng.stats() == {}
    assert eng.events() == []


def test_tracker_merges_same_rank_summaries(tmp_path):
    """A layered engine ships TWO summaries per rank (the XLA engine's
    device-plane instruments + its host inner's): the tracker must merge
    them section-wise, not overwrite."""
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(1, obs_dir=str(tmp_path))
    try:
        t._obs_ingest(json.dumps(
            {"rank": 0, "engine": "PyRobustEngine",
             "metrics": {"counters": {"op.allreduce.count": 3}},
             "recovery": [{"ts": 1.0, "phase": "link_error"}]}))
        t._obs_ingest(json.dumps(
            {"rank": 0, "engine": "XLAEngine",
             "metrics": {"gauges": {"xla.device_ops": 5.0}},
             "recovery": [{"ts": 2.0, "phase": "reform"}]}))
        merged = t._obs_reports[0]
        assert merged["metrics"]["counters"]["op.allreduce.count"] == 3
        assert merged["metrics"]["gauges"]["xla.device_ops"] == 5.0
        assert [e["phase"] for e in merged["recovery"]] == \
            ["link_error", "reform"]
        t._write_obs_report()
        report = json.loads((tmp_path / "obs_report.json").read_text())
        assert report["aggregate"]["xla.device_ops"]["max"] == 5.0
    finally:
        t.stop()


# ------------------------------------------------------------ distributed
@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
def test_distributed_counts_agree(engine, tmp_path):
    """A 4-rank fixed-op job must report IDENTICAL op counts and byte
    totals on every rank, and the tracker must aggregate them into the
    per-job report (min == max for every count)."""
    from rabit_tpu.tracker.launch_local import launch

    world, ndata, niter = 4, 600, 3
    code = launch(world, [sys.executable, "tests/workers/obs_worker.py",
                          str(ndata), str(niter)],
                  extra_env={"RABIT_ENGINE": engine},
                  obs_dir=str(tmp_path))
    assert code == 0
    snaps = []
    for r in range(world):
        f = tmp_path / f"stats.rank{r}.json"
        assert f.exists(), f"rank {r} never dumped stats"
        snaps.append(json.loads(f.read_text()))
    counts = [s["counters"]["op.allreduce.count"] for s in snaps]
    byts = [s["counters"]["op.allreduce.bytes"] for s in snaps]
    assert counts == [niter] * world
    assert byts == [niter * ndata * 4] * world
    bcounts = [s["counters"]["op.broadcast.count"] for s in snaps]
    bbytes = [s["counters"]["op.broadcast.bytes"] for s in snaps]
    assert bcounts == [niter] * world
    assert len(set(bbytes)) == 1  # same payload bytes on every rank
    # latency histograms carry percentiles
    lat = snaps[0]["histograms"]["op.allreduce.seconds"]
    assert lat["count"] == niter and 0 < lat["p50"] <= lat["p99"]
    # per-rank event files + the tracker-aggregated report
    for r in range(world):
        assert (tmp_path / f"events.rank{r}.jsonl").exists()
    report = json.loads((tmp_path / "obs_report.json").read_text())
    assert report["ranks_reported"] == list(range(world))
    agg = report["aggregate"]["op.allreduce.count"]
    assert agg["min"] == agg["max"] == niter
    # summed bytes across ranks
    total = sum(json.loads((tmp_path / f"stats.rank{r}.json").read_text())
                ["counters"]["op.allreduce.bytes"] for r in range(world))
    assert total == world * niter * ndata * 4


def test_soak_obs_report_with_kill(tmp_path):
    """Acceptance gate: a 4-rank pyrobust soak round with one injected
    kill writes a tracker-aggregated report containing per-op
    count/bytes/latency percentiles for all ranks AND a recovery
    timeline matching the documented phase sequence; obs_report renders
    it (and a Chrome trace) without error."""
    from rabit_tpu.tools import obs_report, soak

    # seed 3 -> kill point 1,2,1,0 (rank 1 dies at v2 seq1): fires
    # MID-span, so the relaunched rank must be REPLAYED the cached
    # seq-0 result and the timeline shows the full documented arc.
    rc = soak.main(["--world", "4", "--rounds", "1", "--seed", "3",
                    "--kills", "1", "--engine", "pyrobust",
                    "--ndata", "400", "--niter", "3",
                    "--obs-dir", str(tmp_path)])
    assert rc == 0
    round_dir = tmp_path / "round0"
    report = json.loads((round_dir / "obs_report.json").read_text())
    assert report["ranks_reported"] == [0, 1, 2, 3]
    for rank in "0123":
        hists = report["ranks"][rank]["metrics"]["histograms"]
        lat = hists["op.allreduce.seconds"]
        assert lat["count"] > 0 and lat["p50"] > 0 and lat["p99"] > 0
        assert report["ranks"][rank]["metrics"]["counters"][
            "op.allreduce.bytes"] > 0
    phases = [e["phase"] for e in report["recovery_timeline"]]
    # the documented protocol order, as a subsequence of the merged
    # timeline (doc/observability.md)
    it = iter(phases)
    assert all(p in it for p in
               ["link_error", "rendezvous", "replay", "resume"]), phases
    # the report and the per-rank event dumps render cleanly
    assert obs_report.main([str(round_dir),
                            "--chrome", str(tmp_path / "trace.json")]) == 0
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["traceEvents"], "Chrome trace is empty"
    assert {e["ph"] for e in trace["traceEvents"]} <= {"X", "i"}
