"""CI-scale version of the data-scale distributed kmeans soak.

tools/dist_kmeans_soak.py is the full harness (10M rows, world 8 —
numbers in doc/benchmarks.md "distributed kmeans at data scale"); this
test runs the same code path at a CI-friendly size: world 4, 400k rows,
hashed staging, one injected death, device-plane reform, final
agreement.
"""
import json
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dist_kmeans_soak_with_death(native_lib):
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "dist_kmeans_soak.py"),
         "--world", "4", "--rows", "400000", "--iters", "5",
         "--die-rank", "2", "--die-version", "3"],
        cwd=ROOT, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    out = proc.stdout
    assert "SOAK final-agreement OK" in out
    m = re.search(r"SOAK_SUMMARY (\{.*\})", out)
    assert m, out[-2000:]
    summary = json.loads(m.group(1))
    # the death and reform happened and steady state came back
    assert summary["death_iter_gap_s"] is not None
    assert summary["reform_iter_gap_s"] is not None
    assert summary["iter_s_post_recovery"] is not None
