"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""
import os

# Force CPU regardless of the host's TPU plugin (the axon sitecustomize
# pins JAX_PLATFORMS, so env alone is not enough — set the config too).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def empty_engine():
    """A fresh world-of-1 engine, finalized afterwards."""
    import rabit_tpu

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    yield
    rabit_tpu.finalize()


@pytest.fixture(scope="session")
def native_lib():
    """Build librabit_tpu.so once per session (skip tests if build fails)."""
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).resolve().parent.parent
    lib = root / "rabit_tpu" / "native" / "lib" / "librabit_tpu.so"
    proc = subprocess.run(["make", "-C", str(root / "rabit_tpu" / "native")],
                          capture_output=True, text=True)
    if proc.returncode != 0 or not lib.exists():
        pytest.skip(f"native library build failed:\n{proc.stderr}")
    return lib
