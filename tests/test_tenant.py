"""Multi-tenant tracker tests.

Covers the ISSUE 8 contract (doc/fault_tolerance.md "Multi-tenant
tracker"):

* wire back-compat BOTH directions: the default job's hello is
  byte-identical to the pre-multi-tenant layout (a new worker still
  speaks to an old tracker), and a pre-PR-8 client (classic MAGIC, no
  job field) lands in the ``default`` job and runs next to a named job;
* per-job isolation: rank maps, rendezvous rounds, heartbeat verdicts
  and elastic scale-down targets are job-scoped — one tenant's failure
  storm never moves a co-tenant's state;
* admission control (``max_jobs`` / ``max_total_workers``): typed
  reject replies on the wire, typed budgeted :class:`AdmissionError`
  at the engine, and re-admission the moment a finishing job frees the
  slot (never a hang, never a serve-loop crash);
* serve-loop hardening: port scanners / HTTP probes / garbage length
  prefixes are logged and dropped (typed reject where the magic
  parsed), and the accept thread survives to serve the next real round;
* tracker HA with N jobs in flight: a crash with one job mid-formation-
  barrier and another mid-epoch (pending rescale) replays BOTH journals
  from ``state_dir/<job>/`` and both jobs complete;
* job lifecycle: created on first registrant, finished on unanimous
  goodbye, orphan-GC'd when the last member vanishes — with ``job.*``
  counters and per-job obs reports under ``--obs-dir/<job>/``;
* the slow two-tenant chaos soak gate (``tools/soak.py --tenants``).
"""
import json
import os
import socket
import struct
import time

import pytest

from rabit_tpu import obs
from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.tracker import Tracker

pytestmark = pytest.mark.tenant


# ------------------------------------------------------------- helpers
def _hello(addr, cmd, task_id, job=P.DEFAULT_JOB, world=0):
    s = socket.create_connection(addr, timeout=30)
    P.send_hello(s, cmd, task_id, world, job=job)
    return s


def _register(addr, task_id, cmd=P.CMD_START, job=P.DEFAULT_JOB,
              world=0, port=12345):
    """Send one rendezvous registration; the caller recvs the reply
    once the round completes (the send never blocks, so rounds can be
    driven sequentially without threads)."""
    s = _hello(addr, cmd, task_id, job=job, world=world)
    P.send_str(s, "127.0.0.1")
    P.send_u32(s, port)
    return s


def _round(addr, cmds, job=P.DEFAULT_JOB, world=0):
    socks = {t: _register(addr, t, c, job=job, world=world)
             for t, c in cmds.items()}
    out = {}
    for t, s in socks.items():
        out[t] = P.TopologyReply.recv(s)
        s.close()
    return out


def _shutdown(addr, task_id, job=P.DEFAULT_JOB):
    _hello(addr, P.CMD_SHUTDOWN, task_id, job=job).close()


def _wait(pred, deadline_sec=10.0):
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


class _FakeSock:
    """Captures sendall bytes (wire-layout pinning without a socket)."""

    def __init__(self):
        self.data = b""

    def sendall(self, b):
        self.data += bytes(b)


# ------------------------------------------------- wire back-compat
def test_default_job_hello_is_byte_identical_to_classic():
    """Back-compat direction 1: a new worker whose job id is the
    default sends EXACTLY the pre-multi-tenant byte stream — an old
    tracker cannot tell the difference.  A named job switches to the
    MAGIC_JOB extension (an old tracker drops it at the magic check
    instead of silently merging two tenants into one barrier)."""
    new = _FakeSock()
    P.send_hello(new, P.CMD_START, "task7", 4)
    old = _FakeSock()
    # the classic layout, written out by hand
    old.sendall(struct.pack("<I", P.MAGIC))
    for s in (P.CMD_START, "task7"):
        raw = s.encode()
        old.sendall(struct.pack("<I", len(raw)) + raw)
    old.sendall(struct.pack("<I", 4))
    assert new.data == old.data

    named = _FakeSock()
    P.send_hello(named, P.CMD_START, "task7", 4, job="tenantA")
    assert named.data[:4] == struct.pack("<I", P.MAGIC_JOB)
    assert named.data != new.data


def test_job_id_validation():
    assert P.valid_job_id("default")
    assert P.valid_job_id("exp-01.b")
    assert not P.valid_job_id("")
    assert not P.valid_job_id(".hidden")
    assert not P.valid_job_id("a/b")
    assert not P.valid_job_id("../evil")
    assert not P.valid_job_id("x" * 65)


def test_mixed_version_clients_share_tracker():
    """Back-compat direction 2: a pre-PR-8 handshake (classic MAGIC, no
    job field) lands in the ``default`` job and completes its round
    while a NAMED job of a different world is mid-flight on the same
    tracker — neither sees the other's ranks or world."""
    t = Tracker(2)  # default job world: 2
    t.start()
    try:
        addr = (t.host, t.port)
        # named job, world 3 (from the hint): park 2 of 3 registrants
        parked = {tid: _register(addr, tid, job="named", world=3)
                  for tid in ("n0", "n1")}
        assert _wait(lambda: t._job_get("named") is not None)

        # the OLD-STYLE clients (no job field) run a full round meanwhile
        old = _round(addr, {"0": P.CMD_START, "1": P.CMD_START})
        assert {r.world for r in old.values()} == {2}
        assert {r.rank for r in old.values()} == {0, 1}

        # the named job is untouched by that: still parked, then its
        # third registrant completes a WORLD-3 round
        parked["n2"] = _register(addr, "n2", job="named", world=3)
        replies = {tid: P.TopologyReply.recv(s)
                   for tid, s in parked.items()}
        for s in parked.values():
            s.close()
        assert {r.world for r in replies.values()} == {3}
        assert {r.rank for r in replies.values()} == {0, 1, 2}
        # isolated rank maps: same universe of small ranks, two jobs
        assert t._job_get("named")._rank_of.keys() == {"n0", "n1", "n2"}
        assert t._rank_of.keys() == {"0", "1"}  # default-job alias
    finally:
        t.stop()


# ---------------------------------------------------- fault isolation
def test_heartbeat_verdicts_are_job_scoped():
    """The same task_id exists in two jobs; tenant A's SIGKILL-shaped
    heartbeat EOF must scale down ONLY tenant A — tenant B's identically
    named worker keeps its membership and no cross-job liveness event
    leaks."""
    t = Tracker(2, min_workers=1, heartbeat_miss=10.0)
    t.start()
    hbs = []
    try:
        addr = (t.host, t.port)
        for job in ("ja", "jb"):
            r = _round(addr, {"0": P.CMD_START, "1": P.CMD_START},
                       job=job, world=2)
            assert {x.world for x in r.values()} == {2}
            for tid in ("0", "1"):
                hb = _hello(addr, P.CMD_HEARTBEAT, tid, job=job)
                P.send_u32(hb, 50)
                P.send_u32(hb, 1)
                hbs.append((job, tid, hb))
        # kill tenant ja's task "0" channel (EOF, no bye)
        for job, tid, hb in hbs:
            if job == "ja" and tid == "0":
                hb.close()
        ja, jb = t._job_get("ja"), t._job_get("jb")
        assert _wait(lambda: ja._target_world == 1)
        assert jb._target_world is None
        assert "0" in ja._lost_tasks and "0" not in jb._lost_tasks
        assert not any(e.get("name") == "liveness"
                       and e.get("phase") == "lost"
                       for e in jb._events)
    finally:
        t.stop()
        for _j, _t, hb in hbs:
            hb.close()


# -------------------------------------------------- admission control
def test_admission_max_jobs_typed_reject_on_the_wire():
    """Over --max-jobs capacity: the registration gets the typed reject
    frame (never parks, never crashes the serve loop), and the tracker
    still serves the admitted job's rounds afterwards."""
    t = Tracker(1, max_jobs=1)
    t.start()
    try:
        addr = (t.host, t.port)
        a = _register(addr, "a0", job="jobA", world=1)
        assert P.TopologyReply.recv(a).world == 1
        a.close()

        s = _register(addr, "b0", job="jobB", world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.RejectReply)
        assert reply.code == P.REJECT_MAX_JOBS
        assert "max-jobs" in reply.reason

        # the admitted job keeps being served (recover round completes)
        s = _register(addr, "a0", cmd=P.CMD_RECOVER, job="jobA", world=1)
        assert P.TopologyReply.recv(s).world == 1
        s.close()
        assert t._svc_counters["job.admission.rejected.jobs"] >= 1
    finally:
        t.stop()


def test_admission_max_total_workers_typed_reject():
    t = Tracker(2, max_total_workers=3)
    t.start()
    try:
        addr = (t.host, t.port)
        r = _round(addr, {"a0": P.CMD_START, "a1": P.CMD_START},
                   job="jobA", world=2)
        assert {x.world for x in r.values()} == {2}
        s = _register(addr, "b0", job="jobB", world=2)  # 2 + 2 > 3
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.RejectReply)
        assert reply.code == P.REJECT_MAX_WORKERS
    finally:
        t.stop()


def test_admission_reject_leaves_no_state_behind(tmp_path):
    """Rejects must be stateless: an over-capacity submission creates
    NO JobState (nothing for the sweeps to iterate forever) and NO
    state_dir/<job>/ directory — a long-lived tracker bombarded with
    distinct over-capacity job names cannot grow without bound."""
    t = Tracker(1, max_jobs=1, state_dir=str(tmp_path))
    t.start()
    try:
        addr = (t.host, t.port)
        a = _register(addr, "a0", job="jobA", world=1)
        P.TopologyReply.recv(a)
        a.close()
        for i in range(5):
            s = _register(addr, f"z{i}", job=f"zombie{i}", world=1)
            assert isinstance(P.TopologyReply.recv_or_reject(s),
                              P.RejectReply)
            s.close()
        with t._jobs_lock:
            names = set(t._jobs)
        assert not any(n.startswith("zombie") for n in names), names
        assert not any(p.name.startswith("zombie")
                       for p in tmp_path.iterdir()), list(tmp_path.iterdir())
    finally:
        t.stop()


def test_admission_engine_raises_typed_admission_error():
    """The engine surfaces an exhausted admission budget as
    AdmissionError — a LinkError (same contract as TrackerLostError),
    carrying the tracker's code/reason — never a hang."""
    import rabit_tpu
    from rabit_tpu.engine.pysocket import (AdmissionError, LinkError,
                                           PySocketEngine)

    assert issubclass(AdmissionError, LinkError)
    assert "AdmissionError" in rabit_tpu.__all__

    t = Tracker(1, max_jobs=1)
    t.start()
    occupier = None
    try:
        addr = (t.host, t.port)
        occupier = _register(addr, "a0", job="jobA", world=1)
        P.TopologyReply.recv(occupier)

        eng = PySocketEngine()
        t0 = time.monotonic()
        with pytest.raises(AdmissionError) as ei:
            eng.init({"rabit_tracker_uri": t.host,
                      "rabit_tracker_port": t.port,
                      "rabit_task_id": "b0", "rabit_world_size": 1,
                      "rabit_job_id": "jobB",
                      "rabit_admission_retries": 2,
                      "rabit_backoff_base_ms": 10})
        assert ei.value.code == P.REJECT_MAX_JOBS
        assert time.monotonic() - t0 < 30  # budgeted, not a hang
    finally:
        t.stop()
        if occupier is not None:
            occupier.close()


def test_admission_reject_backoff_admit_under_concurrency():
    """The contended shape (ISSUE 15 satellite): N submitters race ONE
    --max-jobs 1 slot.  Every one of them must eventually run —
    typed RejectReply → backoff → re-poll → admission as the previous
    job's goodbye frees the slot — and the tracker must end with NO
    zombie JobState (every admitted job finished, nothing parked,
    nothing holding capacity)."""
    import threading

    t = Tracker(1, max_jobs=1)
    t.start()
    n = 6
    results: dict[int, dict] = {i: {"rejects": 0, "admitted": False}
                                for i in range(n)}
    errors: list[str] = []

    def submitter(i: int) -> None:
        addr = (t.host, t.port)
        job = f"c{i}"
        try:
            for attempt in range(200):
                s = _register(addr, f"w{i}", job=job, world=1)
                reply = P.TopologyReply.recv_or_reject(s)
                s.close()
                if isinstance(reply, P.RejectReply):
                    assert reply.code == P.REJECT_MAX_JOBS, reply
                    results[i]["rejects"] += 1
                    time.sleep(0.02 * (1 + (attempt % 4)))  # backoff
                    continue
                assert reply.world == 1
                results[i]["admitted"] = True
                time.sleep(0.02)          # hold the slot briefly
                _shutdown(addr, f"w{i}", job=job)
                return
            errors.append(f"submitter {i} never admitted")
        except Exception as e:  # noqa: BLE001 — surfaced as a failure
            errors.append(f"submitter {i}: {type(e).__name__}: {e}")

    try:
        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(n)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not errors, errors
        assert all(r["admitted"] for r in results.values()), results
        # Contention existed: with one slot and six racers, SOMEONE
        # must have seen the typed reject.
        assert sum(r["rejects"] for r in results.values()) > 0, results
        # No zombie JobState: every job that ever held capacity is
        # done, nothing is parked, and the books balance.
        assert _wait(lambda: all(
            j.done for j in t._job_list() if j.touched), 20), \
            [(j.name, j.done, j.touched) for j in t._job_list()]
        for j in t._job_list():
            with j._pending_lock:
                assert not j._pending, j.name
        assert t._svc_counters["job.finished"] >= n
        assert t._svc_counters["job.admission.rejected.jobs"] >= 1
    finally:
        t.stop()


def test_admission_readmits_when_finishing_job_drains():
    """The single-job ergonomics papercut: a submission rejected at
    capacity while the first job is finishing must be ADMITTED once the
    finishing job completes — the tracker frees capacity at the
    unanimous goodbye and lingers for the rejected worker's re-poll,
    instead of rejecting it for the full budget."""
    t = Tracker(1, max_jobs=1)
    t.start()
    try:
        addr = (t.host, t.port)
        a = _register(addr, "a0", job="jobA", world=1)
        P.TopologyReply.recv(a)
        a.close()

        s = _register(addr, "b0", job="jobB", world=1)
        assert isinstance(P.TopologyReply.recv_or_reject(s),
                          P.RejectReply)
        s.close()

        _shutdown(addr, "a0", job="jobA")  # jobA completes, slot frees
        assert _wait(lambda: t._job_get("jobA") is None)

        # the re-poll lands: same submission now gets a topology
        s = _register(addr, "b0", job="jobB", world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.TopologyReply) and reply.world == 1
    finally:
        t.stop()


# ------------------------------------------- serve-loop hardening
def test_stray_clients_logged_dropped_never_crash():
    """A port scanner / HTTP probe / garbage client on the tracker port
    must be dropped (typed reject where a partial handshake parsed) —
    and the accept thread must survive to serve the next real job."""
    t = Tracker(2)
    t.start()
    try:
        addr = (t.host, t.port)
        # 1) HTTP probe: bad magic, silently dropped (EOF back)
        s = socket.create_connection(addr, timeout=10)
        s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        try:
            assert s.recv(64) == b""  # closed, no reply bytes
        except ConnectionResetError:
            pass  # RST (unread probe bytes at close) == dropped too
        s.close()
        # 2) valid magic, absurd string length: typed reject reply
        s = socket.create_connection(addr, timeout=10)
        s.sendall(struct.pack("<I", P.MAGIC))
        s.sendall(struct.pack("<I", 1 << 30))  # "cmd" length
        reply = P.TopologyReply.recv_or_reject(s)
        assert isinstance(reply, P.RejectReply)
        assert reply.code == P.REJECT_BAD_HANDSHAKE
        s.close()
        # 3) valid magic, non-utf8 cmd bytes: typed reject, no crash
        s = socket.create_connection(addr, timeout=10)
        s.sendall(struct.pack("<I", P.MAGIC))
        s.sendall(struct.pack("<I", 4) + b"\xff\xfe\xfd\xfc")
        assert isinstance(P.TopologyReply.recv_or_reject(s),
                          P.RejectReply)
        s.close()
        # 4) partial handshake then EOF
        s = socket.create_connection(addr, timeout=10)
        s.sendall(struct.pack("<I", P.MAGIC)[:2])
        s.close()
        # 5) bad job id on the extended hello: typed reject
        s = socket.create_connection(addr, timeout=10)
        s.sendall(struct.pack("<I", P.MAGIC_JOB))
        raw = b"../evil"
        s.sendall(struct.pack("<I", len(raw)) + raw)
        assert isinstance(P.TopologyReply.recv_or_reject(s),
                          P.RejectReply)
        s.close()

        # 6) garbage AFTER a well-formed hello (oversized host length
        # on a registration): still a typed reject, still counted
        s = _hello(addr, P.CMD_START, "t0")
        s.sendall(struct.pack("<I", 1 << 29))  # "host" length
        assert isinstance(P.TopologyReply.recv_or_reject(s),
                          P.RejectReply)
        s.close()

        # the serve loop survived all of it: a real round completes
        r = _round(addr, {"0": P.CMD_START, "1": P.CMD_START})
        assert {x.world for x in r.values()} == {2}
        assert t._svc_counters["job.handshake.dropped"] >= 4
    finally:
        t.stop()


def test_launcher_rejects_malformed_job_before_spawning():
    from rabit_tpu.tracker.launch_local import launch
    from rabit_tpu.tracker.launch_pod import launch_pod

    with pytest.raises(ValueError, match="not a valid job id"):
        launch(1, ["true"], job="bad/name")
    with pytest.raises(ValueError, match="not a valid job id"):
        launch_pod(["true"], n_local=1, job="../evil")


# --------------------------------------------------- tracker HA, N jobs
def _journal_flushed(job) -> bool:
    return (job._state_store.newest_version() or 0) >= job._state_seq


def test_tracker_restart_replays_all_job_journals(tmp_path):
    """The HA gate shape: a tracker crash with job "alpha" mid-
    formation-barrier and job "beta" mid-epoch (joiner parked, rescale
    target pending) replays BOTH journals from state_dir/<job>/ and
    both jobs complete on the restarted tracker."""
    t1 = Tracker(2, max_workers=4, state_dir=str(tmp_path))
    t1.start()
    addr1 = (t1.host, t1.port)

    ra = _round(addr1, {"a0": P.CMD_START, "a1": P.CMD_START},
                job="alpha", world=2)
    rb = _round(addr1, {"b0": P.CMD_START, "b1": P.CMD_START},
                job="beta", world=2)
    # alpha: half-posted formation barrier
    post = _hello(addr1, P.CMD_FORMBAR, "a0", job="alpha")
    alpha1, beta1 = t1._job_get("alpha"), t1._job_get("beta")
    assert _wait(lambda: "a0" in alpha1._formbar_posted
                 and _journal_flushed(alpha1))
    # beta: joiner parks -> pending 2->3 rescale epoch
    joiner = _register(addr1, "b2", job="beta", world=0)
    assert _wait(lambda: beta1._target_world == 3
                 and _journal_flushed(beta1))
    t1.stop()  # crash with both jobs mid-flight
    post.close()
    joiner.close()

    # journals landed per job under state_dir/<job>/
    assert (tmp_path / "alpha").is_dir() and (tmp_path / "beta").is_dir()

    t2 = Tracker(2, max_workers=4, state_dir=str(tmp_path))
    try:
        alpha, beta = t2._job_get("alpha"), t2._job_get("beta")
        assert alpha is not None and beta is not None
        assert alpha._formbar_posted == {"a0"}
        assert alpha._formbar_state == "open"
        assert alpha._rank_of == {tid: r.rank for tid, r in ra.items()}
        assert beta._members == {"b0", "b1"}
        assert beta._target_world == 3 and beta._epoch == 0
        t2.start()
        addr2 = (t2.host, t2.port)
        # alpha's barrier completes from the replayed half
        socks = [_hello(addr2, P.CMD_FORMBAR, tid, job="alpha")
                 for tid in ("a0", "a1")]
        for s in socks:
            assert P.recv_u32(s) == 1
            s.close()
        # beta's rescale completes with the epoch bumped
        r2 = _round(addr2, {"b0": P.CMD_RESCALE, "b1": P.CMD_RESCALE,
                            "b2": P.CMD_START}, job="beta")
        assert {r.world for r in r2.values()} == {3}
        assert {r.epoch for r in r2.values()} == {1}
        assert {r2["b0"].rank, r2["b1"].rank} == \
               {rb["b0"].rank, rb["b1"].rank}
        # both jobs finish -> the restarted service drains cleanly
        for tid in ("a0", "a1"):
            _shutdown(addr2, tid, job="alpha")
        for tid in ("b0", "b1", "b2"):
            _shutdown(addr2, tid, job="beta")
        assert _wait(t2._service_done)
    finally:
        t2.stop()


# ----------------------------------------------- lifecycle + obs dirs
def test_orphan_gc_collects_vanished_job_and_service_exits():
    """A job whose members ALL vanish (heartbeat EOF, no goodbye) is
    orphan-GC'd: capacity freed, ``job.*`` counters bumped, lifecycle
    events in the job timeline — and the serve loop exits instead of
    waiting forever on a goodbye that can never come."""
    t = Tracker(2, job_gc_sec=1.0)
    t.start()
    try:
        addr = (t.host, t.port)
        _round(addr, {"s0": P.CMD_START, "s1": P.CMD_START},
               job="doomed", world=2)
        hbs = []
        for tid in ("s0", "s1"):
            hb = _hello(addr, P.CMD_HEARTBEAT, tid, job="doomed")
            P.send_u32(hb, 50)
            P.send_u32(hb, 1)
            hbs.append(hb)
        job = t._job_get("doomed")
        time.sleep(0.3)
        for hb in hbs:
            hb.close()  # SIGKILL shape: EOF without the bye
        assert _wait(lambda: job.done, deadline_sec=15)
        assert t._svc_counters["job.orphan_gc"] == 1
        phases = [e.get("phase") for e in job._events
                  if e.get("name") == "job"]
        assert phases == ["created", "orphan_gc"]
        # the last job is gone -> the service drains on its own
        # (generous bound: GC grace + sweep cadence on a loaded box)
        t.join(timeout=30)
        assert not t._thread.is_alive()
    finally:
        t.stop()


def test_per_job_obs_reports_nest_under_job_dirs(tmp_path):
    """The default job's report keeps the pre-tenant root layout; a
    named job's nests under obs_dir/<job>/ with the job name and the
    service section stamped in — and obs_report renders both."""
    from rabit_tpu.tools import obs_report

    t = Tracker(1, obs_dir=str(tmp_path))
    t.start()
    try:
        addr = (t.host, t.port)
        s = _register(addr, "n0", job="teno", world=1)
        P.TopologyReply.recv(s)
        s.close()
        summary = {"rank": 0, "engine": "PyRobustEngine", "job": "teno",
                   "metrics": {"counters": {"op.allreduce.count": 3}},
                   "recovery": []}
        p = _hello(addr, P.CMD_PRINT, "n0", job="teno")
        P.send_str(p, obs.OBS_SUMMARY_PREFIX + json.dumps(summary))
        p.close()
        _shutdown(addr, "n0", job="teno")  # finish -> report written
        path = tmp_path / "teno" / "obs_report.json"
        assert _wait(path.exists)
        report = json.loads(path.read_text())
        assert report["job"] == "teno"
        assert report["service"]["counters"]["job.created"] >= 1
        assert report["ranks_reported"] == [0]
        import io

        buf = io.StringIO()
        obs_report.render_report(report, out=buf)
        out = buf.getvalue()
        assert "teno" in out and "job.created" in out

        # default job keeps the root layout (legacy single-job surface)
        t._obs_ingest(json.dumps({"rank": 0, "metrics": {}, "recovery": []}))
        t._write_obs_report()
        assert (tmp_path / "obs_report.json").exists()
    finally:
        t.stop()


def test_worker_env_carries_job_id():
    t = Tracker(3)
    try:
        env = t.worker_env(task_id="5")
        assert "RABIT_JOB_ID" not in env  # default job: classic env
        env = t.worker_env(task_id="5", job="expA")
        assert env["RABIT_JOB_ID"] == "expA"
        assert env["RABIT_WORLD_SIZE"] == "3"
    finally:
        t.stop()


def test_engine_rejects_malformed_job_id():
    from rabit_tpu.engine.pysocket import PySocketEngine
    from rabit_tpu.utils.checks import RabitError

    eng = PySocketEngine()
    with pytest.raises(RabitError):
        eng.init({"rabit_tracker_uri": "127.0.0.1",
                  "rabit_tracker_port": 1,
                  "rabit_job_id": "../evil"})


# ----------------------------------------------------- the slow gate
@pytest.mark.slow
def test_soak_tenants():
    """The headline isolation gate: two jobs train concurrently against
    one shared tracker under wire chaos; every worker of tenant0 is
    SIGKILLed mid-training, and tenant1's final model must be bit-exact
    vs a solo fixed-world run while the tracker survives and orphan-GCs
    the dead job (see tools/soak.py --tenants)."""
    from rabit_tpu.tools import soak

    rc = soak.main(["--tenants", "2", "--chaos", "--rounds", "1",
                    "--seed", "99", "--ndata", "2000", "--niter", "8"])
    assert rc == 0, "tenant soak failed — scenario printed above"
