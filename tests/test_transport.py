"""Pluggable transports: shm rings, integrity framing, live failover.

Five layers under test (doc/fault_tolerance.md "Transports, integrity
& failover"):

* the primitives — ShmRing wrap-around/peek semantics, the frame
  codec's encode/decode round trip and corruption detection, the
  transport-keyed tuning-cache rows;
* link pairs in one process — framed shm round trips, write-side
  ``torn`` damage escalating as a typed IntegrityError, read-side
  ``flip`` damage transparently absorbed by the bounded re-read;
* the negotiation handshake — default config stays on the classic
  byte-identical wire, features activate only in the offer
  intersection (mixed-config worlds interoperate in both directions),
  same-host-group peers upgrade to shm, cross-group stay tcp;
* the chaos contract — flip/corrupt/torn/doorbell ride the same
  seeded deterministic schedules as every other kind, and with framing
  on EVERY injected corruption pairs with an ``integrity.detected``
  count (zero silent corruption);
* end to end — the transport parity matrix (worlds 2/4/5, shm and
  mixed same-host/cross-host topologies, every schedule, the
  zero/1/odd-size payload ladder), kill-point replay over shm under
  pyrobust, and a mid-job torn ring failing over to tcp with the
  failover on the obs counters — plus the engine-hygiene lint over
  rabit_tpu/transport/.  The randomized gate is
  ``tools/soak.py --transport shm [--chaos]`` (slow-marked here).
"""
import ast
import json
import os
import pathlib
import socket
import struct
import sys
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.transport

REPO = pathlib.Path(__file__).resolve().parent.parent


def _launch(worker, world, env, args=(), obs_dir=None):
    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_BACKOFF_BASE_MS": "10", **env}
    return launch(world, [sys.executable, f"tests/workers/{worker}.py",
                          *args], extra_env=env, obs_dir=obs_dir)


class _Counters:
    """Events stub recording transport-layer counters/events."""

    def __init__(self):
        self.counts = {}
        self.events = []

    def counter(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n

    def event(self, name, **fields):
        self.events.append((name, fields))


# ---------------------------------------------------------------- rings
def test_shm_ring_roundtrip_and_wraparound(tmp_path):
    from rabit_tpu.transport.shm import ShmRing

    ring, path = ShmRing.create(str(tmp_path), 64)
    peer = ShmRing.attach(path)
    os.unlink(path)
    rng = np.random.default_rng(7)
    sent = bytearray()
    got = bytearray()
    # Push ~10 ring capacities through in ragged chunks so the cursors
    # wrap many times and every copy path splits at the boundary.
    payload = rng.integers(0, 256, 640, dtype=np.uint8).tobytes()
    off = 0
    while off < len(payload) or len(got) < len(payload):
        if off < len(payload):
            n = ring.write(memoryview(payload)[off:off + 37])
            sent += payload[off:off + n]
            off += n
        buf = bytearray(29)
        n = peer.read(memoryview(buf))
        got += buf[:n]
    assert bytes(got) == payload
    assert ring.avail() == 0 and ring.space() == 64


def test_shm_ring_peek_does_not_consume(tmp_path):
    from rabit_tpu.transport.shm import ShmRing

    ring, path = ShmRing.create(str(tmp_path), 32)
    peer = ShmRing.attach(path)
    os.unlink(path)
    ring.write(memoryview(b"abcdefgh"))
    first = bytearray(4)
    peer.peek(0, memoryview(first))
    again = bytearray(4)
    peer.peek(0, memoryview(again))
    assert bytes(first) == bytes(again) == b"abcd"
    assert peer.avail() == 8  # nothing consumed
    mid = bytearray(3)
    peer.peek(2, memoryview(mid))
    assert bytes(mid) == b"cde"
    peer.advance(8)
    assert peer.avail() == 0


# --------------------------------------------------------------- frames
def test_frame_codec_roundtrip_split_feeds():
    from rabit_tpu.transport.framing import FrameDecoder, encode_frames

    payload = bytes(range(256)) * 37  # multi-frame at small frame_max
    parts = encode_frames([memoryview(payload)], frame_max=1000)
    wire = b"".join(bytes(p) for p in parts)
    dec = FrameDecoder(peer=1)
    out = bytearray()
    # Feed in awkward chunk sizes straddling every boundary.
    for i in range(0, len(wire), 31):
        dec.feed(wire[i:i + 31])
        buf = bytearray(4096)
        while True:
            n = dec.take(memoryview(buf))
            if not n:
                break
            out += buf[:n]
    assert bytes(out) == payload


def test_frame_codec_detects_each_corruption():
    from rabit_tpu.transport.base import IntegrityError
    from rabit_tpu.transport.framing import FrameDecoder, encode_frames

    payload = b"the wire is not to be trusted" * 20
    wire = bytearray(
        b"".join(bytes(p)
                 for p in encode_frames([memoryview(payload)])))
    for pos in (4, len(wire) // 2, len(wire) - 1):  # body, mid, trailer
        damaged = bytearray(wire)
        damaged[pos] ^= 0x10
        ev = _Counters()
        dec = FrameDecoder(peer=3, events=ev)
        with pytest.raises(IntegrityError):
            dec.feed(bytes(damaged))
        assert ev.counts.get("integrity.detected") == 1
    # a corrupted length field is also a detection, not a hang
    damaged = bytearray(wire)
    struct.pack_into("<I", damaged, 0, 0xFFFFFF00)
    ev = _Counters()
    dec = FrameDecoder(peer=3, events=ev)
    with pytest.raises(IntegrityError):
        dec.feed(bytes(damaged))
    assert ev.counts.get("integrity.detected") == 1


# ----------------------------------------------------- tuning-cache key
def test_tuning_cache_transport_keyed_rows():
    from rabit_tpu.sched import TuningCache

    tcp = TuningCache.from_bench({"4096": {"tree": 100.0, "ring": 10.0}},
                                 4, transport="tcp")
    shm = TuningCache.from_bench({"4096": {"tree": 10.0, "ring": 100.0}},
                                 4, transport="shm")
    merged = dict(tcp.table)
    merged.update(shm.table)
    cache = TuningCache(merged)
    assert cache.pick("allreduce", 4096, 4) == "tree"
    assert cache.pick("allreduce", 4096, 4, "tcp") == "tree"
    assert cache.pick("allreduce", 4096, 4, "shm") == "ring"
    # no bleed: a transport with no rows misses to None (static), it
    # never borrows the other transport's winner
    only_tcp = TuningCache(dict(tcp.table))
    assert only_tcp.pick("allreduce", 4096, 4, "shm") is None


# ------------------------------------------------------- chaos contract
def test_chaos_corruption_kinds_grammar_and_determinism():
    from rabit_tpu.chaos import parse_plan
    from rabit_tpu.utils.checks import RabitError

    spec = ("23:flip@io=0.2;corrupt@io=0.1;torn@shm=0.3;"
            "doorbell@shm=0.2;flip@shm=0.1;budget=200")

    def drive(plan):
        for _ in range(300):
            plan.io()
            plan.shm(("torn", "doorbell", "stall"))
            plan.shm(("flip", "corrupt"))
        return list(plan.log)

    log_a = drive(parse_plan(spec, identity="2"))
    log_b = drive(parse_plan(spec, identity="2"))
    assert log_a and log_a == log_b      # same seed -> same schedule
    assert drive(parse_plan(spec.replace("23:", "24:", 1),
                            identity="2")) != log_a
    kinds = {k for _, k, _, _ in log_a}
    assert {"flip", "torn", "doorbell"} <= kinds
    # shm-only kinds cannot fire at wire sites and vice versa
    for bad in ("1:torn@io=0.1", "1:doorbell@io=0.1",
                "1:reset@shm=0.1", "1:flip@connect=0.1",
                "1:torn@accept=0.1"):
        with pytest.raises((RabitError, ValueError)):
            parse_plan(bad, identity="0")


def test_chaos_mutate_is_deterministic_and_never_noop():
    from rabit_tpu.chaos import parse_plan

    a = parse_plan("5:flip@io=1.0", identity="1")
    b = parse_plan("5:flip@io=1.0", identity="1")
    for kind in ("flip", "corrupt", "torn"):
        va = bytearray(b"0123456789abcdef")
        vb = bytearray(b"0123456789abcdef")
        a.mutate(va, kind)
        b.mutate(vb, kind)
        assert va == vb                      # same seed, same damage
        assert va != b"0123456789abcdef"     # and never a no-op


# ------------------------------------------------------------ link pairs
def _shm_pair(tmp_path, frames=True, plan_w=None, plan_r=None,
              ev_w=None, ev_r=None, ring=65536, timeout=10.0,
              retries=3):
    from rabit_tpu.transport.base import NULL_EVENTS
    from rabit_tpu.transport.shm import ShmLink, ShmRing

    a, b = socket.socketpair()
    r1, p1 = ShmRing.create(str(tmp_path), ring)
    r2, p2 = ShmRing.create(str(tmp_path), ring)
    w = ShmLink(a, 1, r1, ShmRing.attach(p2), timeout,
                ev_w or NULL_EVENTS, frames=frames, plan=plan_w,
                retries=retries)
    r = ShmLink(b, 0, r2, ShmRing.attach(p1), timeout,
                ev_r or NULL_EVENTS, frames=frames, plan=plan_r,
                retries=retries)
    os.unlink(p1)
    os.unlink(p2)
    return w, r


def test_shm_link_framed_roundtrip_threaded(tmp_path):
    w, r = _shm_pair(tmp_path, ring=4096)  # payload >> ring: must wrap
    rng = np.random.default_rng(3)
    payload = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    err = []

    def writer():
        try:
            w.sendv([payload[:333], payload[333:]])
        except Exception as e:  # noqa: BLE001 — re-raised on the main thread
            err.append(e)

    t = threading.Thread(target=writer)
    t.start()
    out = r.recv_exact(len(payload))
    t.join(timeout=30)
    assert not err, err
    assert bytes(out) == payload
    w.close()
    r.close()


def test_shm_link_torn_write_escalates_typed(tmp_path):
    from rabit_tpu.chaos import parse_plan
    from rabit_tpu.transport.base import IntegrityError, LinkError

    ev = _Counters()
    plan = parse_plan("9:torn@shm=1.0*1", identity="1")
    w, r = _shm_pair(tmp_path, plan_w=plan, ev_r=ev)
    w.sendall(b"x" * 512)
    assert [k for _, k, _, _ in plan.log] == ["torn"]
    with pytest.raises(IntegrityError) as ei:
        r.recv_exact(512)
    assert isinstance(ei.value, LinkError)   # recovery path catches it
    assert ei.value.link is r                # failover attribution
    assert ev.counts.get("integrity.detected") == 1
    w.close()
    r.close()


def test_shm_link_read_flip_recovered_by_reread(tmp_path):
    from rabit_tpu.chaos import parse_plan

    ev = _Counters()
    plan = parse_plan("11:flip@shm=1.0*1", identity="0")
    w, r = _shm_pair(tmp_path, plan_r=plan, ev_r=ev)
    w.sendall(b"payload under transient read damage")
    out = r.recv_exact(35)
    assert bytes(out) == b"payload under transient read damage"
    assert [k for _, k, _, _ in plan.log] == ["flip"]
    assert ev.counts.get("integrity.detected") == 1
    assert ev.counts.get("integrity.retry") == 1  # one re-read sufficed
    assert ev.counts.get("integrity.recovered") == 1
    w.close()
    r.close()


def test_shm_link_doorbell_swallow_is_absorbed(tmp_path):
    from rabit_tpu.chaos import parse_plan

    plan = parse_plan("13:doorbell@shm=1.0*1", identity="1")
    w, r = _shm_pair(tmp_path, plan_w=plan)
    t0 = time.monotonic()
    w.sendall(b"wakeup-less")
    out = r.recv_exact(11)
    assert bytes(out) == b"wakeup-less"
    assert time.monotonic() - t0 < 5  # bounded poll, not the timeout
    assert [k for _, k, _, _ in plan.log] == ["doorbell"]
    w.close()
    r.close()


def test_pump_abort_drops_framed_backlog_and_restores_timeout():
    """The exception-path pump exit must DROP the claimed tx backlog:
    recovery rewires every link from scratch, and a blocking flush to a
    peer that is itself aborting would delay the in-flight LinkError by
    up to the full link timeout."""
    from rabit_tpu.transport.tcp import TcpLink

    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    tx = TcpLink(a, 1, 5.0, frames=True)
    bufs = [memoryview(bytes(1 << 20))]
    tx.pump_begin()
    while tx.poll_sendv(bufs):      # claim, then fill the kernel buffer
        pass
    assert tx.tx_pending()          # backlog left: peer is not reading
    tx.pump_abort()
    assert not tx.tx_pending()      # dropped, not flushed
    assert a.gettimeout() == 5.0    # blocking state restored
    a.close()
    b.close()


def test_wait_readable_writable_poll_semantics():
    from rabit_tpu.transport.base import wait_readable_writable

    a, b = socket.socketpair()
    b.sendall(b"x")
    r, w = wait_readable_writable([a], [a], 0.2)
    assert a in r and a in w
    a.close()
    b.close()
    # A closed fd degrades to ValueError (callers map it to LinkError),
    # never an unbounded block.
    with pytest.raises(ValueError):
        wait_readable_writable([a], [], 0.01)


def test_accept_refuses_degenerate_rings(tmp_path):
    """A dialer (version skew / corrupt offer) shipping rings below the
    floor must be refused at attach: both sides land on tcp instead of
    a ring that can stall every send to the link timeout."""
    from rabit_tpu.tracker import protocol as P
    from rabit_tpu.transport.base import TransportConfig
    from rabit_tpu.transport.factory import LinkFactory
    from rabit_tpu.transport.shm import ShmRing

    a, b = socket.socketpair()
    lf = LinkFactory(TransportConfig(transport="shm"), timeout=5.0)
    lf.set_topology(0, [0, 0])
    tiny_tx, p1 = ShmRing.create(str(tmp_path), 16)
    tiny_rx, p2 = ShmRing.create(str(tmp_path), 16)
    answers = []

    def dialer():
        P.send_str(a, p1)
        P.send_str(a, p2)
        answers.append(P.recv_u32(a))

    t = threading.Thread(target=dialer)
    t.start()
    link = lf._accept_shm(b, 1, frames=False)
    t.join(timeout=10)
    assert link is None             # caller falls through to _tcp_link
    assert answers == [0]           # dialer told to stay tcp too
    tiny_tx.close()
    tiny_rx.close()
    a.close()
    b.close()


def test_dial_rejects_tiny_negotiated_ring():
    """A negotiated ring size below the floor (skewed peer offer) takes
    the documented dialer-abort path, keeping the handshake protocol in
    sync — the acceptor reads the empty-path abort and stays tcp."""
    from rabit_tpu.tracker import protocol as P
    from rabit_tpu.transport.base import TransportConfig
    from rabit_tpu.transport.factory import LinkFactory

    a, b = socket.socketpair()
    lf = LinkFactory(TransportConfig(transport="shm"), timeout=5.0)
    lf.set_topology(0, [0, 0])
    link = lf._dial_shm(a, 1, {"shm": 16}, frames=False)
    assert link is None             # caller falls through to _tcp_link
    assert P.recv_str(b, max_len=4096) == ""   # the protocol abort
    a.close()
    b.close()


def test_tcp_link_flip_pairing_injected_equals_detected():
    """With framing on, EVERY injected wire corruption is matched by
    exactly one integrity.detected count — the zero-silent-corruption
    contract at the link level."""
    from rabit_tpu.chaos import ChaosSocket, parse_plan
    from rabit_tpu.transport.base import IntegrityError
    from rabit_tpu.transport.tcp import TcpLink

    injected = detected = 0
    for seed in range(5):
        a, b = socket.socketpair()
        plan = parse_plan(f"{seed}:flip@io=0.5*1;corrupt@io=0.5*1",
                          identity="0")
        ev = _Counters()
        tx = TcpLink(a, 1, 10.0, frames=True)
        rx = TcpLink(ChaosSocket(b, plan, 0), 0, 10.0, ev, frames=True)
        tx.sendall(b"q" * 4096)
        try:
            rx.recv_exact(4096)
        except IntegrityError:
            pass
        injected += plan.injected
        detected += ev.counts.get("integrity.detected", 0)
        tx.close()
        rx.close()
    assert injected > 0, "seeds injected nothing — vacuous"
    assert injected == detected


# ----------------------------------------------- in-process negotiation
def _run_world(world, params_per_rank, fn, engine="pysocket"):
    """Run ``world`` engines on threads against an in-process tracker;
    ``fn(eng, rank)`` is the body.  Returns the engines (shut down)."""
    from rabit_tpu.engine.pysocket import PySocketEngine
    from rabit_tpu.engine.robust import PyRobustEngine
    from rabit_tpu.tracker.tracker import Tracker

    cls = PyRobustEngine if engine == "pyrobust" else PySocketEngine
    trk = Tracker(world, "127.0.0.1", 0)
    trk.start()
    engines = [cls() for _ in range(world)]
    errs = []

    def run(i):
        try:
            p = {"rabit_tracker_uri": trk.host,
                 "rabit_tracker_port": trk.port,
                 "rabit_task_id": str(i), "rabit_world_size": world,
                 "rabit_timeout_sec": 30, "rabit_obs": 1,
                 **params_per_rank[i]}
            engines[i].init(p)
            fn(engines[i], engines[i].rank)
            engines[i].shutdown()
        except Exception as e:  # noqa: BLE001 — re-raised on the main thread
            errs.append((i, e))
    threads = [threading.Thread(target=run, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    trk.stop()
    assert not errs, errs
    return engines


def _allreduce_ok(eng, rank):
    from rabit_tpu.ops import ReduceOp

    a = np.arange(1000, dtype=np.float64) + rank
    eng.allreduce(a, ReduceOp.SUM)
    w = eng.world_size
    np.testing.assert_allclose(
        a, w * np.arange(1000, dtype=np.float64) + w * (w - 1) / 2)


def _link_snapshot(eng):
    """(peer, kind, framed) per wired link — captured INSIDE the run
    body (shutdown clears the link table)."""
    return sorted((peer, link.kind, bool(getattr(link, "_frames", False)))
                  for peer, link in eng._links.items())


@pytest.mark.parametrize("side_a,side_b", [
    # mixed-config interop BOTH directions: the featured side degrades
    # to the classic wire against a default-config peer (exactly what a
    # mixed-version world looks like once negotiation is in play)
    ({"rabit_wire_integrity": "crc32c"}, {}),
    ({}, {"rabit_wire_integrity": "crc32c"}),
    ({"rabit_transport": "shm"}, {}),
])
def test_negotiation_degrades_to_common_subset(side_a, side_b):
    snaps = {}

    def body(eng, rank):
        snaps[rank] = _link_snapshot(eng)
        _allreduce_ok(eng, rank)
    _run_world(2, {0: side_a, 1: side_b}, body)
    for rank, links in snaps.items():
        ((_peer, kind, framed),) = links
        assert kind == "tcp" and not framed, (rank, links)


def test_negotiation_activates_in_intersection():
    feats = {"rabit_transport": "shm", "rabit_wire_integrity": "crc32c"}
    snaps = {}

    def body(eng, rank):
        snaps[rank] = _link_snapshot(eng)
        _allreduce_ok(eng, rank)
    engines = _run_world(2, {0: dict(feats), 1: dict(feats)}, body)
    for rank, links in snaps.items():
        ((_peer, kind, framed),) = links
        assert kind == "shm" and framed, (rank, links)
    for eng in engines:
        assert eng.stats()["counters"].get("transport.links.shm") == 1


def test_cross_group_peers_stay_tcp(monkeypatch):
    """transport=auto upgrades only same-host-group links: a simulated
    two-host world 4 keeps every cross-group link on tcp."""
    monkeypatch.setenv("RABIT_TRACKER_GROUPS", "0,0,1,1")
    snaps = {}
    groups = {}

    def body(eng, rank):
        snaps[rank] = _link_snapshot(eng)
        groups[rank] = list(eng._groups)
        _allreduce_ok(eng, rank)
    _run_world(4, {i: {"rabit_transport": "auto"} for i in range(4)},
               body)
    checked = 0
    for rank, links in snaps.items():
        for peer, kind, _framed in links:
            same = groups[rank][rank] == groups[rank][peer]
            assert (kind == "shm") == same, (rank, peer, kind)
            checked += 1
    assert checked  # the handout actually wired links


def test_shm_failover_to_tcp_mid_job():
    """A torn ring write mid-job: detected, typed, the link re-dialed
    as TCP through the recover rendezvous — op results stay exact and
    the failover is on the counters."""
    feats = {"rabit_transport": "shm", "rabit_wire_integrity": "crc32c",
             "rabit_timeout_sec": 15}
    final = {}

    obs_label = {}

    def body(eng, rank):
        for _ in range(4):
            _allreduce_ok(eng, rank)
        final[rank] = _link_snapshot(eng)
        obs_label[rank] = eng._obs_transport
    params = {0: dict(feats), 1: dict(feats)}
    params[1]["rabit_chaos"] = "31:torn@shm=1.0*1"
    engines = _run_world(2, params, body, engine="pyrobust")
    failovers = sum(
        e.stats()["counters"].get("transport.failover.shm_to_tcp", 0)
        for e in engines)
    detected = sum(e.stats()["counters"].get("integrity.detected", 0)
                   for e in engines)
    assert failovers >= 1 and detected >= 1
    for rank, links in final.items():
        ((_peer, kind, _framed),) = links
        assert kind == "tcp", f"rank {rank} never failed over to tcp"
        # The obs-streamed wire label degrades with the links: the
        # controller must not file tcp-measured verdicts under @shm.
        assert obs_label[rank] == "tcp", (rank, obs_label)


# --------------------------------------------------- end-to-end matrix
@pytest.mark.parametrize("world", [2, 4, 5])
@pytest.mark.parametrize("sched", ["tree", "ring", "halving", "hier"])
def test_parity_matrix_shm(world, sched):
    """Transport parity: every schedule over a full-shm same-host world
    serves the zero/1/odd-size exact-arithmetic ladder bit-correctly
    (sched_parity self-verifies; inapplicable schedules must fall back,
    not die)."""
    assert _launch("sched_parity", world,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": sched,
                    "RABIT_TRANSPORT": "shm",
                    "RABIT_REDUCE_BUFFER": "4KB"}) == 0


@pytest.mark.parametrize("world,groups", [(4, "0,0,1,1"),
                                          (5, "0,0,0,1,1")])
def test_parity_matrix_mixed_transport(world, groups):
    """Mixed same-host/cross-host worlds: shm intra-group, tcp
    cross-group, hier exercising both in one op — plus integrity
    framing on every link."""
    env = {"RABIT_ENGINE": "pysocket", "RABIT_TRANSPORT": "auto",
           "RABIT_WIRE_INTEGRITY": "crc32c",
           "RABIT_TRACKER_GROUPS": groups}
    for sched in ("static", "hier"):
        assert _launch("sched_parity", world,
                       {**env, "RABIT_SCHED": sched}) == 0


def test_kill_point_replay_over_shm():
    """The flagship two-deaths replay scenario with the whole data
    plane on shm rings + integrity framing: cache/replay recovery must
    serve bit-identical results across the restarts."""
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_TRANSPORT": "shm",
                    "RABIT_WIRE_INTEGRITY": "crc32c",
                    "RABIT_MOCK": "0,0,1,0;1,1,1,0",
                    "RABIT_TIMEOUT_SEC": "15"},
                   args=("1000", "3")) == 0


def test_corruption_pairing_end_to_end(tmp_path):
    """Launched world with seeded wire flips + framing: every injected
    corruption is detected (counters pair in the merged obs report) and
    the job still finishes with self-verified numerics."""
    # ranks=0 scopes the plan to one worker whose ops are all blocking
    # (model_recover issues no async stream), so every fired flip is
    # applied at its own receive and detected before the next consult.
    assert _launch("model_recover", 2,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_WIRE_INTEGRITY": "crc32c",
                    "RABIT_CHAOS": "17:flip@io=0.05*3;ranks=0",
                    "RABIT_TIMEOUT_SEC": "15"},
                   args=("2000", "3"), obs_dir=str(tmp_path)) == 0
    rep = json.loads((tmp_path / "obs_report.json").read_text())
    agg = rep["aggregate"]
    nranks = 2

    def total(name):
        row = agg.get(name)
        return round(row["mean"] * nranks) if row else 0

    injected = total("chaos.injected.flip")
    assert injected >= 1, "seeds injected nothing — vacuous"
    assert total("integrity.detected") == injected


# ------------------------------------------------------- engine hygiene
def test_transport_module_hygiene():
    """The transport layer — and the wire codecs that transform its
    bytes (rabit_tpu/codec/) — ride the engine lint: no bare
    ``except:`` and no raw ``print`` — diagnostics route through the
    structured logger / typed errors like the engines'."""
    offenders = []
    # rabit_tpu/serve/ (ISSUE 15) parses network-originated frames on
    # its data plane: same rules.  rabit_tpu/tracker/ (ISSUE 16) is the
    # sharded control plane every worker registers through: same rules.
    for path in sorted((REPO / "rabit_tpu" / "transport").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "codec").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "sched").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "serve").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "tracker").glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                offenders.append(f"{path.name}:{node.lineno} bare except")
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                offenders.append(f"{path.name}:{node.lineno} raw print")
    assert not offenders, offenders


# ------------------------------------------------------------ soak gate
@pytest.mark.slow
def test_transport_soak_gate():
    """The randomized shm gate: seeded torn/flip corruption over shm
    rings with integrity framing — zero silent corruption (bit-exact
    final vs a tcp reference), live shm→tcp failover visible on the
    counters and timeline — composed with the full --chaos wire mix."""
    from rabit_tpu.tools.soak import main as soak_main

    assert soak_main(["--transport", "shm", "--world", "4",
                      "--rounds", "1", "--ndata", "3000",
                      "--niter", "4"]) == 0
    assert soak_main(["--transport", "shm", "--chaos", "--world", "4",
                      "--rounds", "1", "--ndata", "3000",
                      "--niter", "4", "--seed", "5"]) == 0
