"""Quantized wire codecs (doc/performance.md "Quantized wire codecs").

The contracts pinned here:

* quantize/dequantize round-trips — ``deq(encode(x)) + residual == x``
  bitwise for int8/int4 at ragged sizes (padding tail, zero blocks,
  constant blocks), the bf16 codec byte-identical to the historical
  ``rabit_wire_dtype=bf16`` cast, and ``wire_nbytes`` reporting the
  TRUE encoded size (the honest dispatch accounting that replaced the
  hardcoded ``nbytes //= 2`` special case);
* the hop-path merge is symmetric (both sides of an exchange-schedule
  pairing produce identical bits) and the error-feedback buffer is
  transactional + bounded;
* parameter resolution — the ``rabit_wire_codec`` vocabulary, the
  deprecated ``rabit_wire_dtype=bf16`` alias, block/floor validation;
* the TuningCache codec dimension: rows keyed per codec never answer
  another codec's lookups (mirroring the transport dimension);
* accuracy gates per codec across worlds {2,4,5}: parity vs an in-run
  ``codec=False`` f32 oracle within the documented envelope on every
  schedule, bit-exactness below the size floor and for opted-out ops,
  error-feedback convergence on a repeated-allreduce stream (no
  drift), fused/async buckets with a mixed opt-in/opt-out stream;
* pyrobust kill-point replay with a codec armed: the replayed op is
  bit-identical to the cached result on every rank.
"""
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.codec

CODEC_WORLDS = [2, 4, 5]


def _launch(worker, world, extra_env=None, args=(), tracker_groups=None):
    from rabit_tpu.tracker.launch_local import launch

    saved = os.environ.get("RABIT_TRACKER_GROUPS")
    try:
        if tracker_groups is not None:
            os.environ["RABIT_TRACKER_GROUPS"] = tracker_groups
        else:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        return launch(world, [sys.executable,
                              f"tests/workers/{worker}.py",
                              *map(str, args)], extra_env=extra_env or {})
    finally:
        if saved is None:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        else:
            os.environ["RABIT_TRACKER_GROUPS"] = saved


# ------------------------------------------------------------ round trips
@pytest.mark.parametrize("bits", [8, 4])
@pytest.mark.parametrize("n", [1, 63, 64, 65, 1000, 4096])
def test_blockscale_roundtrip_exact(bits, n):
    """``deq(wire) + enc_res == x`` BITWISE: the residual is computed
    from the same f32 products the dequantize produces, so error
    feedback carries exactly what the wire dropped."""
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    c = BlockScaleCodec(bits, 64, 0)
    rng = np.random.default_rng(n * bits)
    x = rng.standard_normal(n).astype(np.float32)
    st = c.begin(x.copy(), FeedbackBuffer())
    recon = c._deq(st.wire).reshape(-1)[:n] + st.enc_res.reshape(-1)[:n]
    np.testing.assert_array_equal(recon, x)


@pytest.mark.parametrize("bits", [8, 4])
def test_blockscale_edge_blocks(bits):
    """Zero blocks (scale 0) and constant blocks survive exactly-ish:
    a zero block decodes to exact zeros, a constant block to within
    one quantization step."""
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    c = BlockScaleCodec(bits, 64, 0)
    x = np.zeros(128, np.float32)
    st = c.begin(x.copy(), FeedbackBuffer())
    assert not np.any(c._deq(st.wire))
    x = np.full(128, 3.25, np.float32)
    st = c.begin(x.copy(), FeedbackBuffer())
    step = 3.25 / c.qmax
    assert np.abs(c._deq(st.wire).reshape(-1) - 3.25).max() <= step


def test_wire_nbytes_honest():
    """``wire_nbytes`` must equal the ACTUAL encoded byte count — it is
    what schedule selection and the adaptive controller account."""
    from rabit_tpu.codec.base import Bf16Codec
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    assert Bf16Codec().wire_nbytes(1024) == 512  # the historical //= 2
    for bits in (8, 4):
        c = BlockScaleCodec(bits, 64, 0)
        for n in (1, 64, 65, 1000):
            st = c.begin(np.ones(n, np.float32), FeedbackBuffer())
            assert c.wire_nbytes(n * 4) == st.wire.nbytes, (bits, n)
    # int8: 64 payload + 4 scale per 64 f32 = 68/256 ≈ 0.27x
    assert BlockScaleCodec(8, 64, 0).wire_nbytes(256 << 10) \
        == (256 << 10) * 68 // 256


def test_bf16_codec_matches_historical_cast():
    """The refactored Bf16Codec must produce the byte stream of the
    old inline cast: astype(bfloat16).view(uint16)."""
    import ml_dtypes

    from rabit_tpu.codec.base import Bf16Codec

    x = np.random.default_rng(0).standard_normal(257).astype(np.float32)
    w, red = Bf16Codec().encode(x)
    assert red == np.dtype(ml_dtypes.bfloat16)
    expect = x.astype(ml_dtypes.bfloat16).view(np.uint16)
    np.testing.assert_array_equal(w, expect)
    back = Bf16Codec().decode(w, red)
    np.testing.assert_array_equal(
        back, x.astype(ml_dtypes.bfloat16).astype(np.float32))


@pytest.mark.parametrize("bits", [8, 4])
def test_merge_symmetric(bits):
    """Exchange schedules (halving, swing) requantize the SAME
    accumulated values on both sides of a pairing: the merged wire
    blocks must be bit-identical, or cross-rank parity would break."""
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    c = BlockScaleCodec(bits, 64, 0)
    rng = np.random.default_rng(bits)
    x = rng.standard_normal(1000).astype(np.float32)
    y = rng.standard_normal(1000).astype(np.float32)
    sa = c.begin(x.copy(), FeedbackBuffer())
    sb = c.begin(y.copy(), FeedbackBuffer())
    # side A merges B's wire into its own; side B merges A's into its
    # own — both must land on identical bits.
    a, b = sa.wire.copy(), sb.wire.copy()
    c.merge(sa, a, 0, len(a), sb.wire)
    c.merge(sb, b, 0, len(b), sa.wire)
    assert a.tobytes() == b.tobytes()


@pytest.mark.parametrize("bits", [8, 4])
def test_merge_record_flag_skips_ledger_only(bits):
    """``record=False`` (swing's non-recording side of a replicated
    pairing) must merge IDENTICAL bytes while leaving the hop ledger
    untouched — one quantization event, one ledger entry, never two."""
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    c = BlockScaleCodec(bits, 64, 0)
    rng = np.random.default_rng(bits)
    x = rng.standard_normal(500).astype(np.float32)
    y = rng.standard_normal(500).astype(np.float32)
    sa = c.begin(x.copy(), FeedbackBuffer())
    sb = c.begin(x.copy(), FeedbackBuffer())
    src = c.begin(y.copy(), FeedbackBuffer()).wire
    a, b = sa.wire.copy(), sb.wire.copy()
    c.merge(sa, a, 0, len(a), src, True)
    c.merge(sb, b, 0, len(b), src, False)
    assert a.tobytes() == b.tobytes()
    assert np.any(sa.hop) and not np.any(sb.hop)


# ------------------------------------------------------- error feedback
def test_feedback_buffer_transactional_and_bounded():
    from rabit_tpu.codec.feedback import FeedbackBuffer

    fb = FeedbackBuffer(max_streams=2)
    assert fb.residual(("int8", 10)) is None
    r = np.ones(10, np.float32)
    fb.commit(("int8", 10), r)
    np.testing.assert_array_equal(fb.residual(("int8", 10)), r)
    # LRU bound: a third stream evicts the least-recently-used.
    fb.commit(("int8", 20), np.ones(20, np.float32))
    fb.residual(("int8", 10))  # touch: 20 is now LRU
    fb.commit(("int8", 30), np.ones(30, np.float32))
    assert fb.residual(("int8", 20)) is None
    assert fb.residual(("int8", 10)) is not None
    assert len(fb) == 2


def test_begin_never_mutates_feedback():
    """``begin`` reads the carried residual but must not advance it —
    pyrobust retries re-encode identical wire bytes."""
    from rabit_tpu.codec.blockscale import BlockScaleCodec
    from rabit_tpu.codec.feedback import FeedbackBuffer

    c = BlockScaleCodec(8, 64, 0)
    fb = FeedbackBuffer()
    x = np.random.default_rng(3).standard_normal(500).astype(np.float32)
    fb.commit(("int8", 500), np.full(500, 0.01, np.float32))
    before = fb.residual(("int8", 500)).copy()
    s1 = c.begin(x.copy(), fb)
    s2 = c.begin(x.copy(), fb)
    np.testing.assert_array_equal(fb.residual(("int8", 500)), before)
    assert s1.wire.tobytes() == s2.wire.tobytes()


# ------------------------------------------------------------- resolution
def test_factory_vocabulary_and_alias():
    from rabit_tpu import codec as codec_mod
    from rabit_tpu.utils.checks import RabitError

    assert codec_mod.resolve(None, "native", None, 4096) is None
    assert codec_mod.resolve("none", "bf16", None, 4096) is None
    assert codec_mod.resolve(None, "bf16", None, 4096).name == "bf16"
    c = codec_mod.resolve("int8", "native", 128, 1 << 20)
    assert (c.name, c.block, c.min_bytes) == ("int8", 128, 1 << 20)
    assert codec_mod.resolve("int4", "bf16", None, 0).name == "int4"
    # fp8 family: canonical names plus the short alias
    assert codec_mod.resolve("fp8e4m3", "native", None, 0).name == "fp8e4m3"
    assert codec_mod.resolve("fp8e5m2", "native", None, 0).name == "fp8e5m2"
    assert codec_mod.make("fp8").name == "fp8e4m3"
    with pytest.raises(RabitError):
        codec_mod.make("fp7")
    with pytest.raises(RabitError):
        codec_mod.make("int8", block=3)  # odd
    with pytest.raises(RabitError):
        codec_mod.make("int8", block=8192)  # too large
    with pytest.raises(RabitError):
        codec_mod.make("int8", min_bytes=-1)


def test_eligibility_is_replicated_config():
    """Eligibility sees only replicated inputs: dtype, op, size, the
    uniform codec config — f64/MAX/sub-floor payloads ride classic."""
    from rabit_tpu import codec as codec_mod
    from rabit_tpu.ops import MAX, SUM

    c = codec_mod.make("int8")
    assert c.eligible(np.float32, SUM, 1 << 20)
    assert not c.eligible(np.float64, SUM, 1 << 20)
    assert not c.eligible(np.float32, MAX, 1 << 20)
    assert not c.eligible(np.float32, SUM, 100)  # under the floor
    b = codec_mod.make("bf16")
    assert b.eligible(np.float32, SUM, 4)  # bf16 has no floor


# ------------------------------------------------------ tuner dimension
def test_tuning_cache_codec_dimension(tmp_path):
    """Codec-keyed rows are isolated per codec AND per transport —
    picks never bleed across wire formats (mirrors the transport
    dimension's isolation contract)."""
    from rabit_tpu.sched.tuner import TuningCache

    assert TuningCache.table_kind("allreduce") == "allreduce"
    assert TuningCache.table_kind("allreduce", "shm") == "allreduce@shm"
    assert TuningCache.table_kind("allreduce", "tcp", "int8") \
        == "allreduce+int8"
    assert TuningCache.table_kind("allreduce", "shm", "int8") \
        == "allreduce@shm+int8"
    f32 = TuningCache.from_bench({"4096": {"tree": 100.0, "ring": 10.0}},
                                 4, candidates={"tree", "ring"})
    q = TuningCache.from_bench({"4096": {"tree": 10.0, "ring": 100.0}},
                               4, candidates={"tree", "ring"},
                               codec="int8")
    f32.table.update(q.table)
    f32.save(str(tmp_path))
    cache = TuningCache.load(str(tmp_path))
    assert cache.pick("allreduce", 4096, 4) == "tree"
    assert cache.pick("allreduce", 4096, 4, codec="none") == "tree"
    assert cache.pick("allreduce", 4096, 4, codec="int8") == "ring"
    assert cache.pick("allreduce", 4096, 4, codec="int4") is None
    assert cache.pick("allreduce", 4096, 4, "shm", "int8") is None
    cache.merge_online("allreduce", 6, 8192, "swing", codec="int4")
    assert cache.pick("allreduce", 8192, 6, codec="int4") == "swing"
    # The none-codec pick at world 6 must NOT see int4's world-6 row:
    # it takes the nearest-world fallback to the f32 rows instead.
    assert cache.pick("allreduce", 8192, 6) == "tree"
    assert cache.pick("allreduce", 8192, 6, codec="bf16") is None


def test_span_costs_scoped_by_wire_format():
    """The controller's schedule evidence is scoped per wire format:
    full-width spans (per-op opt-outs, ineligible dtypes, pre-codec
    8-field emitters) never feed the codec-keyed cost windows, and
    vice versa."""
    from rabit_tpu.obs.span import SpanMerger

    m = SpanMerger()
    # int8-wire op (seq 0) and a full-width opt-out op (seq 1), plus a
    # legacy 8-field span (seq 2) from a pre-codec emitter.
    for rank, d in ((0, 0.0), (1, 0.1)):
        m.add(rank, [[0, 0, 0, "allreduce", "ring", 1 << 20,
                      10.0 + d, 11.0 + d, "int8"]], 2)
        m.add(rank, [[1, 0, 0, "allreduce", "ring", 1 << 20,
                      12.0 + d, 15.0 + d, "none"]], 2)
        m.add(rank, [[2, 0, 0, "allreduce", "ring", 1 << 20,
                      16.0 + d, 19.0 + d]], 2)
    int8 = m.sched_costs("int8")
    none = m.sched_costs("none")
    assert int8[("ring", 1 << 20)]["n"] == 1
    assert none[("ring", 1 << 20)]["n"] == 2  # opt-out + legacy span
    assert abs(int8[("ring", 1 << 20)]["mean_sec"] - 1.0) < 1e-6
    assert abs(none[("ring", 1 << 20)]["mean_sec"] - 3.0) < 1e-6
    assert m.sched_costs("int4") == {}


# ------------------------------------------------- the accuracy matrix
# Tier-1 budget (ISSUE 15 satellite): bf16 + int8 (the headline wire)
# are the fast codec-axis representatives; the int4 end-to-end cell
# rides `-m slow` with the worlds matrix — its quantize/merge
# exactness stays covered by the fast round-trip units above.
@pytest.mark.parametrize("codec", [
    "bf16", "int8",
    pytest.param("int4", marks=pytest.mark.slow),
    "fp8e4m3",
    pytest.param("fp8e5m2", marks=pytest.mark.slow)])
def test_codec_accuracy_world4(codec):
    """The flagship world: every schedule (incl. hier via a two-host
    group handout), the EF stream, fused/async and the mixed
    opt-in/opt-out bucket — all against the in-run f32 oracle."""
    assert _launch("codec_worker", 4,
                   extra_env={"RABIT_ENGINE": "pysocket",
                              "RABIT_WIRE_CODEC": codec},
                   tracker_groups="0,0,1,1") == 0


@pytest.mark.slow
@pytest.mark.parametrize("codec", ["bf16", "int8", "int4",
                                   "fp8e4m3", "fp8e5m2"])
@pytest.mark.parametrize("world", [2, 5])
def test_codec_accuracy_worlds(codec, world):
    """The rest of the {2,4,5} worlds matrix (world 4 runs fast above):
    odd worlds hit the ragged block partitions, world 2 the static
    tree-only dispatch."""
    assert _launch("codec_worker", world,
                   extra_env={"RABIT_ENGINE": "pysocket",
                              "RABIT_WIRE_CODEC": codec}) == 0


def test_codec_robust_replay_bit_identical():
    """Kill-point replay with int8 armed: the relaunched rank's
    replayed op must serve the EXACT cached bytes (fingerprinted,
    cross-rank agreed) — the codec composes below the cache."""
    assert _launch("codec_replay", 3,
                   extra_env={"RABIT_ENGINE": "pyrobust",
                              "RABIT_WIRE_CODEC": "int8",
                              "RABIT_MOCK": "1,0,1,0"}) == 0


# ------------------------------------------------- learn end-to-end
def _learn_workers_runnable() -> bool:
    """The learn workers pin ``jax_num_cpu_devices`` at import; on jax
    versions without that option they cannot start at all (the same
    environmental condition that fails test_boosting/test_learn_dist's
    distributed cases).  These gates run exactly where those do."""
    import subprocess

    probe = ("import jax; "
             "jax.config.update('jax_num_cpu_devices', 1)")
    return subprocess.run(
        [sys.executable, "-c", probe], capture_output=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}).returncode == 0


def test_boosting_histogram_int8_end_to_end(tmp_path):
    """Boosting trains over int8-quantized histogram allreduces (the
    bulk traffic the codec targets, deliberately opted IN): split
    decisions taken on the quantized sums still learn the function to
    the same accuracy gate as the f32 run, and the model is identical
    on every rank (the quantized wire is deterministic + replicated —
    the worker's allgather parity check pins it)."""
    if not _learn_workers_runnable():
        pytest.skip("learn workers cannot start on this jax "
                    "(jax_num_cpu_devices unsupported)")
    rng = np.random.default_rng(0)
    X = rng.uniform(-1, 1, (600, 2)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    assert _launch("boosting_dist", 2, args=(str(tmp_path),),
                   extra_env={"RABIT_ENGINE": "pysocket",
                              "RABIT_WIRE_CODEC": "int8",
                              # quantize EVERY histogram level, not
                              # just the ones over the default floor
                              "RABIT_CODEC_MIN_BYTES": "0"}) == 0


def test_lbfgs_opt_out_bit_exact_with_codec(tmp_path):
    """The L-BFGS solver opts every collective out (``codec=False``):
    training with int8 armed must produce a BYTE-identical model to
    the codec-free run — the opt-out keeps the solver on the exact
    classic wire."""
    if not _learn_workers_runnable():
        pytest.skip("learn workers cannot start on this jax "
                    "(jax_num_cpu_devices unsupported)")

    def write_libsvm(path, Xs, ys):
        with open(path, "w") as f:
            for row, label in zip(Xs, ys):
                feats = " ".join(f"{j + 1}:{v:.6f}"
                                 for j, v in enumerate(row))
                f.write(f"{int(label)} {feats}\n")

    world = 2
    rng = np.random.default_rng(7)
    X = rng.standard_normal((160, 6)).astype(np.float32)
    w_true = rng.standard_normal(6)
    y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(160)).astype(
        np.float32)
    for r in range(world):
        write_libsvm(tmp_path / f"part{r}.libsvm", X[r::world],
                     y[r::world])
    pattern = str(tmp_path / "part%d.libsvm")
    models = {}
    for codec in ("none", "int8"):
        out = str(tmp_path / f"model.{codec}")
        assert _launch("linear_dist", world,
                       args=(pattern, "logistic", out,
                             "reg_L2=0.1", "max_lbfgs_iter=8"),
                       extra_env={"RABIT_ENGINE": "pyrobust",
                                  "RABIT_WIRE_CODEC": codec,
                                  "RABIT_CODEC_MIN_BYTES": "0"}) == 0
        with open(out, "rb") as f:
            models[codec] = f.read()
    assert models["none"] == models["int8"], \
        "lbfgs model changed under an armed codec — opt-out leaked"


def test_codec_counters_surface_in_report():
    """The codec telemetry (ops, logical vs wire bytes, ratio) lands in
    the obs aggregate and obs_report renders the table."""
    import io
    import json

    import rabit_tpu
    from rabit_tpu.tools import obs_report

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    agg = {"codec.ops": {"min": 4, "mean": 4, "max": 4},
           "codec.ops.int8": {"min": 4, "mean": 4, "max": 4},
           "codec.bytes.logical": {"min": 4e6, "mean": 4e6, "max": 4e6},
           "codec.bytes.wire": {"min": 1.1e6, "mean": 1.1e6,
                                "max": 1.1e6},
           "codec.bytes_saved": {"min": 2.9e6, "mean": 2.9e6,
                                 "max": 2.9e6},
           "codec.feedback.norm.mean": {"min": 0.001, "mean": 0.001,
                                        "max": 0.002}}
    out = io.StringIO()
    obs_report.render_codec(agg, out)
    text = out.getvalue()
    assert "wire codec" in text and "int8" in text
    assert "0.275" in text  # wire/logical ratio
    assert "error-feedback" in text
    json.dumps(agg)  # the shape is the report's aggregate shape
