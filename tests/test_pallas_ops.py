"""Pallas kernel tests (interpret mode on the virtual CPU mesh).

The fused k-means stats kernel is checked against a plain-XLA reference;
the ring allreduce runs under shard_map on the 8-device CPU mesh via the
distributed TPU interpreter and is checked against psum/pmax.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.kmeans_kernel import kmeans_stats_fused
from rabit_tpu.ops.ring_allreduce import ring_allreduce_pallas


def _xla_stats(centroids, x, valid):
    cn = centroids / (np.linalg.norm(centroids, axis=1, keepdims=True)
                      + 1e-12)
    sim = x @ cn.T
    assign = sim.argmax(axis=1)
    k = centroids.shape[0]
    onehot = np.zeros((x.shape[0], k), np.float32)
    onehot[np.arange(x.shape[0]), assign] = valid
    sums = onehot.T @ x
    counts = onehot.sum(axis=0)
    return np.concatenate([sums, counts[:, None]], axis=1)


@pytest.mark.parametrize("n,d,k", [(512, 256, 64), (300, 100, 10)])
def test_kmeans_stats_fused_matches_xla(n, d, k):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, d)).astype(np.float32)
    cent = rng.standard_normal((k, d)).astype(np.float32)
    valid = (rng.random(n) > 0.1).astype(np.float32)

    got = np.asarray(kmeans_stats_fused(
        jnp.asarray(cent), jnp.asarray(x), jnp.asarray(valid), block=256))
    want = _xla_stats(cent, x, valid)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_kmeans_stats_fused_all_negative_sim():
    # all similarities negative: padded zero-centroids must not win
    rng = np.random.default_rng(1)
    d, k, n = 100, 3, 64
    cent = np.abs(rng.standard_normal((k, d))).astype(np.float32)
    x = -np.abs(rng.standard_normal((n, d))).astype(np.float32)
    valid = np.ones(n, np.float32)
    got = np.asarray(kmeans_stats_fused(
        jnp.asarray(cent), jnp.asarray(x), jnp.asarray(valid), block=64))
    want = _xla_stats(cent, x, valid)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)
    assert got[:, -1].sum() == n  # every point assigned to a real cluster


def _mesh(ndev):
    return Mesh(np.array(jax.devices()[:ndev]), ("x",))


@pytest.mark.parametrize("ndev,size,op", [
    (4, 4 * 128, ReduceOp.SUM),
    (4, 1000, ReduceOp.SUM),       # non-aligned, padded
    (8, 2048, ReduceOp.MAX),
    (2, 257, ReduceOp.MIN),
])
def test_ring_allreduce_pallas(ndev, size, op):
    if len(jax.devices()) < ndev:
        pytest.skip("not enough virtual devices")
    mesh = _mesh(ndev)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((ndev, size)).astype(np.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard[0], "x", op=op,
                                     interpret=True)[None]

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("x"),
                              out_specs=P("x"), check_vma=False))
    out = np.asarray(f(x))
    red = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
           ReduceOp.MIN: np.min}[op]
    want = red(x, axis=0)
    for i in range(ndev):
        np.testing.assert_allclose(out[i], want, rtol=1e-5, atol=1e-5)


def test_ring_allreduce_world1():
    mesh = _mesh(1)
    x = jnp.arange(64, dtype=jnp.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard, "x", interpret=True)

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P(),
                              out_specs=P(), check_vma=False))
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))


def test_ring_allreduce_2d_shape():
    ndev = 4
    if len(jax.devices()) < ndev:
        pytest.skip("not enough virtual devices")
    mesh = _mesh(ndev)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((ndev, 17, 9)).astype(np.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard[0], "x", interpret=True)[None]

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("x"),
                              out_specs=P("x"), check_vma=False))
    out = np.asarray(f(x))
    want = x.sum(axis=0)
    for i in range(ndev):
        np.testing.assert_allclose(out[i], want, rtol=1e-5, atol=1e-5)
