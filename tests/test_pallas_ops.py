"""Pallas kernel tests (interpret mode on the virtual CPU mesh).

The fused k-means stats kernel is checked against a plain-XLA reference;
the ring allreduce runs under shard_map on the 8-device CPU mesh via the
distributed TPU interpreter and is checked against psum/pmax.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.kmeans_kernel import kmeans_stats_fused
from rabit_tpu.ops.ring_allreduce import ring_allreduce_pallas


def _xla_stats(centroids, x, valid):
    cn = centroids / (np.linalg.norm(centroids, axis=1, keepdims=True)
                      + 1e-12)
    sim = x @ cn.T
    assign = sim.argmax(axis=1)
    k = centroids.shape[0]
    onehot = np.zeros((x.shape[0], k), np.float32)
    onehot[np.arange(x.shape[0]), assign] = valid
    sums = onehot.T @ x
    counts = onehot.sum(axis=0)
    return np.concatenate([sums, counts[:, None]], axis=1)


@pytest.mark.parametrize("n,d,k", [(512, 256, 64), (300, 100, 10)])
def test_kmeans_stats_fused_matches_xla(n, d, k):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, d)).astype(np.float32)
    cent = rng.standard_normal((k, d)).astype(np.float32)
    valid = (rng.random(n) > 0.1).astype(np.float32)

    got = np.asarray(kmeans_stats_fused(
        jnp.asarray(cent), jnp.asarray(x), jnp.asarray(valid), block=256))
    want = _xla_stats(cent, x, valid)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_kmeans_stats_fused_all_negative_sim():
    # all similarities negative: padded zero-centroids must not win
    rng = np.random.default_rng(1)
    d, k, n = 100, 3, 64
    cent = np.abs(rng.standard_normal((k, d))).astype(np.float32)
    x = -np.abs(rng.standard_normal((n, d))).astype(np.float32)
    valid = np.ones(n, np.float32)
    got = np.asarray(kmeans_stats_fused(
        jnp.asarray(cent), jnp.asarray(x), jnp.asarray(valid), block=64))
    want = _xla_stats(cent, x, valid)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)
    assert got[:, -1].sum() == n  # every point assigned to a real cluster


def _mesh(ndev):
    return Mesh(np.array(jax.devices()[:ndev]), ("x",))


@pytest.mark.parametrize("ndev,size,op", [
    (4, 4 * 128, ReduceOp.SUM),
    (4, 1000, ReduceOp.SUM),       # non-aligned, padded
    (8, 2048, ReduceOp.MAX),
    (2, 257, ReduceOp.MIN),
])
def test_ring_allreduce_pallas(ndev, size, op):
    if len(jax.devices()) < ndev:
        pytest.skip("not enough virtual devices")
    mesh = _mesh(ndev)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((ndev, size)).astype(np.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard[0], "x", op=op,
                                     interpret=True)[None]

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("x"),
                              out_specs=P("x"), check_vma=False))
    out = np.asarray(f(x))
    red = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
           ReduceOp.MIN: np.min}[op]
    want = red(x, axis=0)
    for i in range(ndev):
        np.testing.assert_allclose(out[i], want, rtol=1e-5, atol=1e-5)


def test_ring_allreduce_world1():
    mesh = _mesh(1)
    x = jnp.arange(64, dtype=jnp.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard, "x", interpret=True)

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P(),
                              out_specs=P(), check_vma=False))
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))


def test_ring_allreduce_2d_shape():
    ndev = 4
    if len(jax.devices()) < ndev:
        pytest.skip("not enough virtual devices")
    mesh = _mesh(ndev)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((ndev, 17, 9)).astype(np.float32)

    def fn(shard):
        return ring_allreduce_pallas(shard[0], "x", interpret=True)[None]

    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("x"),
                              out_specs=P("x"), check_vma=False))
    out = np.asarray(f(x))
    want = x.sum(axis=0)
    for i in range(ndev):
        np.testing.assert_allclose(out[i], want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("op", [ReduceOp.SUM, ReduceOp.MAX, ReduceOp.MIN,
                                ReduceOp.PROD])
def test_ring_allreduce_pallas_bit_equal_psum_world8(op):
    """The production routing contract (rabit_device_impl=pallas_ring):
    at world 8 the kernel's result is BIT-equal to the psum lowering for
    every supported op.  Bitwise, not allclose: the ring combines in a
    fixed rank order and XLA's allreduce must agree exactly for the
    engine to treat the two lowerings as interchangeable — float sums
    are kept associativity-safe by using values with exact float32
    representations."""
    ndev = 8
    if len(jax.devices()) < ndev:
        pytest.skip("not enough virtual devices")
    from rabit_tpu.ops import apply_op_jax

    mesh = _mesh(ndev)
    rng = np.random.default_rng(11)
    # integers in float32: every partial result is exact, so any
    # combining order yields the same bits
    x = rng.integers(-32, 33, size=(ndev, 1000)).astype(np.float32)
    if op == ReduceOp.PROD:
        x = rng.choice(np.array([0.5, 1.0, 2.0], np.float32),
                       size=(ndev, 1000))

    def ring_fn(shard):
        return ring_allreduce_pallas(shard[0], "x", op=op,
                                     interpret=True)[None]

    def psum_fn(shard):
        return apply_op_jax(op, shard[0], "x")[None]

    ring = jax.jit(jax.shard_map(ring_fn, mesh=mesh, in_specs=P("x"),
                                 out_specs=P("x"), check_vma=False))
    psum = jax.jit(jax.shard_map(psum_fn, mesh=mesh, in_specs=P("x"),
                                 out_specs=P("x")))
    got = np.asarray(ring(x))
    want = np.asarray(psum(x))
    np.testing.assert_array_equal(got, want)


def _ell_to_dense(idx, val, d):
    n = idx.shape[0]
    dense = np.zeros((n, d + 1), np.float32)
    np.add.at(dense, (np.arange(n)[:, None], idx), val)
    return dense[:, :d]


@pytest.mark.parametrize("n,d,k,nnz", [(4096, 512, 64, 32),
                                       (2048, 384, 10, 16)])
def test_kmeans_ell_stats_fused_matches_xla(n, d, k, nnz):
    """The fused two-level ELL kernel must agree with the dense oracle
    (float32 compute keeps the comparison exact-ish)."""
    from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

    rng = np.random.default_rng(1)
    idx = rng.integers(0, d, (n, nnz)).astype(np.int32)
    val = rng.standard_normal((n, nnz)).astype(np.float32)
    # sprinkle pad slots (index d, value 0) like to_ell emits
    pad = rng.random((n, nnz)) < 0.2
    idx[pad] = d
    val[pad] = 0.0
    valid = (rng.random(n) > 0.1).astype(np.float32)

    # pad features to a multiple of hi=128 the way prepare_shard does
    d_pad = -(-(d + 1) // 128) * 128
    cent = rng.standard_normal((k, d)).astype(np.float32)
    cent_p = np.pad(cent, ((0, 0), (0, d_pad - d)))

    got = np.asarray(kmeans_ell_stats_fused(
        jnp.asarray(cent_p), jnp.asarray(idx), jnp.asarray(val),
        jnp.asarray(valid), d_pad, group=8, hi=128, block=512,
        compute_dtype=jnp.float32))
    got = np.concatenate([got[:, :d], got[:, -1:]], axis=1)

    dense = _ell_to_dense(idx, val, d)
    want = _xla_stats(cent, dense, valid)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def test_kmeans_ell_stats_fused_validation():
    from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

    cent = jnp.zeros((8, 256))
    idx = jnp.zeros((512, 24), jnp.int32)  # nnz not a power of two
    val = jnp.zeros((512, 24))
    with pytest.raises(ValueError, match="powers of two"):
        kmeans_ell_stats_fused(cent, idx, val, jnp.ones(512), 256,
                               hi=128, block=512)


def test_prepare_shard_ell_fused_path(monkeypatch):
    """On a (faked) TPU backend an over-budget shard takes the fused
    path with slot/row padding, and shard_stats matches the scan path."""
    import jax as _jax

    from rabit_tpu.learn import kmeans as km

    rng = np.random.default_rng(2)
    n, d, nnz, k = 3000, 200, 24, 8
    # well-separated clusters: each row's slots live in its cluster's
    # feature band, so bf16 similarity rounding cannot flip assignments
    owner = rng.integers(0, k, n)
    band = d // k
    idx = (owner[:, None] * band
           + rng.integers(0, band, (n, nnz))).astype(np.int32)
    val = (1.0 + rng.random((n, nnz))).astype(np.float32)
    valid = np.ones(n, np.float32)

    monkeypatch.setattr(_jax, "default_backend", lambda: "tpu")
    shard = km.prepare_shard(idx, val, valid, d, budget=0)
    assert shard[0] == "ell_fused"
    di, dv, dvl, d_pad, nnz_p = shard[2]
    # grouped layout: (n/G, G*nnz_pow2) — the minor dim tiles the 128
    # lanes exactly instead of padding 4x
    assert nnz_p == 32 and di.shape[1] == km._ELL_FUSED_GROUP * 32
    assert (di.shape[0] * km._ELL_FUSED_GROUP) % 2048 == 0
    assert d_pad % 128 == 0

    # centroids aligned with the feature bands (robust assignments)
    cent = np.zeros((k, d), np.float32)
    for j in range(k):
        cent[j, j * band:(j + 1) * band] = 1.0
    model = km.KMeansModel(cent)
    model.normalize()
    # interpret mode (CPU): force it since default_backend is faked
    import rabit_tpu.ops.kmeans_kernel as kk
    orig = kk.kmeans_ell_stats_fused

    def interp(*a, **kw):
        kw["interpret"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(kk, "kmeans_ell_stats_fused", interp)
    got = np.asarray(km.shard_stats_device(model, shard))

    dense = _ell_to_dense(idx, val, d)
    want = _xla_stats(model.centroids, dense, valid)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-1)
