"""Learn-layer tests: data utils, kmeans, L-BFGS, linear models.

Mirrors the reference's app-level coverage (kmeans/linear binaries +
solver, reference: rabit-learn/) with numeric self-verification in the
style of its recovery tests (reference: test/model_recover.cc:29-70).
Single-process here; the distributed paths are covered by the worker
tests in test_learn_dist.py.
"""
import io
import sys

import numpy as np
import pytest


# ---------------------------------------------------------------- data utils
def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for row, label in zip(X, y):
            items = " ".join(
                f"{j}:{v:g}" for j, v in enumerate(row) if v != 0.0)
            f.write(f"{label:g} {items}\n")


def test_libsvm_roundtrip(tmp_path):
    from rabit_tpu.learn import load_libsvm

    rng = np.random.default_rng(0)
    X = rng.standard_normal((20, 7)).astype(np.float32)
    X[rng.random(X.shape) < 0.5] = 0.0
    X[:, -1] = 1.0  # ensure full feat_dim observed
    y = rng.integers(0, 2, 20).astype(np.float32)
    f = tmp_path / "data.libsvm"
    _write_libsvm(f, X, y)

    mat = load_libsvm(str(f))
    assert mat.num_row == 20
    assert mat.feat_dim == 7
    np.testing.assert_allclose(mat.labels, y)
    np.testing.assert_allclose(mat.to_dense(), X, rtol=1e-5)


def test_libsvm_per_rank_filename(tmp_path):
    from rabit_tpu.learn import load_libsvm

    for r in range(2):
        _write_libsvm(tmp_path / f"part{r}.txt",
                      np.eye(3, dtype=np.float32) * (r + 1),
                      np.full(3, r, np.float32))
    mat = load_libsvm(str(tmp_path / "part%d.txt"), rank=1)
    np.testing.assert_allclose(mat.labels, [1, 1, 1])
    assert mat.to_dense()[0, 0] == 2.0


def test_ell_layout():
    from rabit_tpu.learn.data import SparseMat

    mat = SparseMat(
        indptr=np.array([0, 2, 3, 3], np.int64),
        findex=np.array([0, 4, 2], np.int32),
        fvalue=np.array([1.0, 2.0, 3.0], np.float32),
        labels=np.array([1, 0, 1], np.float32),
        feat_dim=5,
    )
    idx, val, labels, valid = mat.to_ell(row_block=4)
    assert idx.shape == (4, 2)
    assert valid.tolist() == [1, 1, 1, 0]
    # row 0: features 0,4; row 2 all padding (sentinel = feat_dim)
    assert idx[0].tolist() == [0, 4]
    assert idx[2].tolist() == [5, 5]
    np.testing.assert_allclose(val[1], [3.0, 0.0])


# ------------------------------------------------------------------- kmeans
def _blob_data(n=256, d=8, k=3, seed=0):
    """Blobs on orthogonal axes — cosine-separable by construction.

    Rows are shuffled so the random-row centroid init (seeded like the
    reference's srand(0), kmeans.cc:96) sees a mixed sample.
    """
    rng = np.random.default_rng(seed)
    centers = np.zeros((k, d), np.float32)
    centers[np.arange(k), np.arange(k)] = 4.0
    X = np.concatenate(
        [centers[i] + 0.1 * rng.standard_normal((n // k + 1, d))
         for i in range(k)])[:n].astype(np.float32)
    rng.shuffle(X)
    from rabit_tpu.learn.data import SparseMat

    nnz = n * d
    return SparseMat(
        indptr=np.arange(0, nnz + 1, d, dtype=np.int64),
        findex=np.tile(np.arange(d, dtype=np.int32), n),
        fvalue=X.reshape(-1),
        labels=np.zeros(n, np.float32),
        feat_dim=d,
    ), X


def _kmeans_oracle(X, cent, iters):
    """Pure-numpy twin of the framework's kmeans loop."""
    c = cent.astype(np.float32).copy()
    k, d = c.shape
    for _ in range(iters):
        cn = c / (np.linalg.norm(c, axis=1, keepdims=True) + 1e-12)
        assign = (X @ cn.T).argmax(axis=1)
        stats = np.zeros((k, d + 1), np.float32)
        for i, a in enumerate(assign):
            stats[a, :d] += X[i]
            stats[a, d] += 1
        assert (stats[:, d] != 0).all(), "oracle hit empty cluster"
        c = (stats[:, :d] / stats[:, d:]).astype(np.float32)
        n = np.linalg.norm(c, axis=1, keepdims=True)
        c = np.where(n < 1e-6, c, c / np.maximum(n, 1e-30)).astype(np.float32)
    return c


def test_kmeans_converges(empty_engine):
    from rabit_tpu.learn import kmeans

    data, X = _blob_data()
    model = kmeans.run(data, num_cluster=3, max_iter=8, row_block=64)
    assert model.centroids.shape == (3, 8)
    # must agree with the numpy twin run from the identical init
    init = kmeans.init_centroids(data, 3, 8, seed=0)
    oracle = _kmeans_oracle(X, init.centroids, 8)
    np.testing.assert_allclose(model.centroids, oracle, rtol=1e-3, atol=1e-3)
    # and the clustering itself must be tight (blobs are separable)
    cn = model.centroids / np.linalg.norm(
        model.centroids, axis=1, keepdims=True)
    xn = X / np.linalg.norm(X, axis=1, keepdims=True)
    assert (xn @ cn.T).max(axis=1).mean() > 0.97


def test_kmeans_device_chain_matches_loop(empty_engine):
    """The device-resident chained path (run(device_chain=...)) must give
    the same centroids as the per-iteration host loop.

    Differences are allowed only where an empty cluster appears (the
    chained path keeps the old centroid instead of erroring), which the
    separable blobs avoid."""
    from rabit_tpu.learn import kmeans

    data, _X = _blob_data()
    ref = kmeans.run(data, num_cluster=3, max_iter=8, row_block=64)
    import rabit_tpu
    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    chained = kmeans.run(data, num_cluster=3, max_iter=8, row_block=64,
                         device_chain=3)  # 3+3+2 split exercises resume
    np.testing.assert_allclose(chained.centroids, ref.centroids,
                               rtol=1e-4, atol=1e-4)


def test_kmeans_checkpoint_resume(empty_engine):
    """Interrupting after version v and rerunning must give the identical
    model (the reference's recovery semantics at app level)."""
    import rabit_tpu
    from rabit_tpu.learn import kmeans

    data, _ = _blob_data()
    full = kmeans.run(data, num_cluster=3, max_iter=6, row_block=64)
    # fresh engine: run 3 iters, "crash", resume to 6
    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    kmeans.run(data, num_cluster=3, max_iter=3, row_block=64)
    resumed = kmeans.run(data, num_cluster=3, max_iter=6, row_block=64)
    np.testing.assert_allclose(
        resumed.centroids, full.centroids, rtol=1e-5, atol=1e-6)


def test_kmeans_stats_against_numpy(empty_engine):
    from rabit_tpu.learn import kmeans

    data, X = _blob_data(n=100, d=8)
    rng = np.random.default_rng(1)
    model = kmeans.KMeansModel(
        rng.standard_normal((4, 8)).astype(np.float32))
    idx, val, _, valid = data.to_ell(pad_index=8, row_block=32)
    stats = kmeans.compute_stats(model, idx, val, valid, row_block=32)
    # numpy oracle
    cn = model.centroids / np.linalg.norm(
        model.centroids, axis=1, keepdims=True)
    assign = (X @ cn.T).argmax(axis=1)
    expect = np.zeros((4, 9), np.float32)
    for i, a in enumerate(assign):
        expect[a, :8] += X[i]
        expect[a, 8] += 1
    np.testing.assert_allclose(stats, expect, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------------- L-BFGS
class _Quadratic:
    """f(w) = 0.5||w - t||^2 — exact minimum known."""

    def __init__(self, target):
        self.target = target

    def eval(self, w):
        return 0.5 * float((w - self.target) @ (w - self.target))

    def calc_grad(self, w):
        return w - self.target

    def init_num_dim(self):
        return len(self.target)

    def init_model(self, w):
        w[:] = 0.0

    def save_state(self):
        return None

    def load_state(self, state):
        pass


def test_lbfgs_quadratic(empty_engine):
    from rabit_tpu.learn import LBFGSSolver

    rng = np.random.default_rng(0)
    target = rng.standard_normal(32)
    solver = LBFGSSolver(_Quadratic(target))
    solver.silent = 1
    solver.lbfgs_stop_tol = 1e-10
    solver.run()
    np.testing.assert_allclose(solver.get_weight(), target, atol=1e-4)


def test_lbfgs_logistic_l1_sparsity(empty_engine, tmp_path):
    """OWL-QN: with L1, irrelevant features must be driven to exact zero."""
    from rabit_tpu.learn import LinearObjFunction

    rng = np.random.default_rng(0)
    n, d = 400, 12
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = np.zeros(d)
    w_true[:3] = [2.0, -3.0, 1.5]
    y = (1 / (1 + np.exp(-(X @ w_true))) > 0.5).astype(np.float32)
    f = tmp_path / "train.libsvm"
    _write_libsvm(f, X, y)

    obj = LinearObjFunction()
    obj.load_data(str(f))
    obj.set_param("objective", "logistic")
    obj.set_param("reg_L1", "2.0")
    obj.set_param("max_lbfgs_iter", "60")
    obj.set_param("silent", "1")
    obj.set_param("row_block", "128")
    obj.lbfgs.run()
    w = obj.lbfgs.get_weight()
    # relevant features survive, most irrelevant ones are exactly zero
    assert abs(w[0]) > 0.1 and abs(w[1]) > 0.1
    assert np.sum(w[3:d] == 0.0) >= 5


# ------------------------------------------------------------------- linear
def _train_linear(tmp_path, objective, seed=0, n=500, d=10, reg_L2="0.01"):
    from rabit_tpu.learn import LinearObjFunction

    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal(d)
    margin = X @ w_true
    if objective == "logistic":
        y = (1 / (1 + np.exp(-margin)) > 0.5).astype(np.float32)
    else:
        y = (margin + 0.01 * rng.standard_normal(n)).astype(np.float32)
    f = tmp_path / "train.libsvm"
    _write_libsvm(f, X, y)

    obj = LinearObjFunction()
    obj.load_data(str(f))
    obj.set_param("objective", objective)
    obj.set_param("reg_L2", reg_L2)
    obj.set_param("max_lbfgs_iter", "80")
    obj.set_param("silent", "1")
    obj.set_param("row_block", "128")
    obj.set_param("model_out", str(tmp_path / "final.model"))
    obj.run()
    return obj, X, y, w_true


def test_linear_regression_recovers_weights(empty_engine, tmp_path):
    obj, X, y, w_true = _train_linear(tmp_path, "linear", reg_L2="0")
    w = obj.model.weight
    np.testing.assert_allclose(w[:10], w_true, atol=0.05)


def test_logistic_classifies(empty_engine, tmp_path):
    obj, X, y, _ = _train_linear(tmp_path, "logistic")
    preds = obj.predict()
    acc = ((preds > 0.5) == (y > 0.5)).mean()
    assert acc > 0.97


def test_model_io_roundtrip(empty_engine, tmp_path):
    from rabit_tpu.learn import LinearModel

    obj, _, _, _ = _train_linear(tmp_path, "logistic", n=100)
    for b64 in (False, True):
        path = tmp_path / ("m.b64" if b64 else "m.bin")
        obj.model.save(str(path), base64_=b64)
        loaded = LinearModel()
        loaded.load(str(path))
        assert loaded.num_feature == obj.model.num_feature
        assert loaded.loss_type == obj.model.loss_type
        np.testing.assert_allclose(
            loaded.weight, obj.model.weight.astype(np.float32), rtol=1e-6)


def test_pred_task_writes_file(empty_engine, tmp_path):
    from rabit_tpu.learn import LinearObjFunction

    obj, X, y, _ = _train_linear(tmp_path, "logistic", n=100)
    pred_obj = LinearObjFunction()
    pred_obj.load_data(str(tmp_path / "train.libsvm"))
    pred_obj.set_param("task", "pred")
    pred_obj.set_param("model_in", str(tmp_path / "final.model"))
    pred_obj.set_param("name_pred", str(tmp_path / "pred.txt"))
    pred_obj.run()
    preds = np.loadtxt(tmp_path / "pred.txt")
    assert len(preds) == 100
    acc = ((preds > 0.5) == (y > 0.5)).mean()
    assert acc > 0.9


def test_hash_features():
    """Signed feature hashing: deterministic, in-range, seed-salted,
    sign-balanced, and inner-product-preserving in expectation (the
    property that makes hashed k-means work)."""
    from rabit_tpu.learn.data import hash_features

    rng = np.random.default_rng(0)
    idx = rng.integers(0, 100_000, (4096, 32)).astype(np.int32)
    val = rng.standard_normal((4096, 32)).astype(np.float32)

    h1, v1 = hash_features(idx, val, 256)
    h2, v2 = hash_features(idx, val, 256)
    np.testing.assert_array_equal(h1, h2)          # deterministic
    np.testing.assert_array_equal(v1, v2)
    assert h1.min() >= 0 and h1.max() < 256
    assert np.array_equal(np.abs(v1), np.abs(val))  # sign-only change
    # the same feature id always lands in the same bucket with the
    # same sign (consistency across rows is what preserves geometry)
    flat = {}
    for f, b, s in zip(idx.ravel(), h1.ravel(), np.sign(v1 / val).ravel()):
        assert flat.setdefault(int(f), (int(b), float(s))) == (int(b), float(s))
    # roughly balanced signs and buckets
    signs = np.array([s for _, s in flat.values()])
    assert 0.4 < (signs > 0).mean() < 0.6
    # a different seed remaps
    h3, _ = hash_features(idx, val, 256, seed=7)
    assert (h3 != h1).mean() > 0.9
    # power-of-two enforcement
    import pytest
    from rabit_tpu.utils.checks import RabitError
    with pytest.raises(RabitError):
        hash_features(idx, val, 200)


def test_kmeans_hashed(empty_engine):
    """hash_dim routes the whole run through signed-hashed feature
    space: the model lives at that width, staging/stats/checkpoints all
    work, and on separable blobs the clustering stays tight (collisions
    are zero-mean under the signed hash)."""
    from rabit_tpu.learn import kmeans
    from rabit_tpu.learn.data import hash_features

    data, X = _blob_data(d=16)
    model = kmeans.run(data, num_cluster=3, max_iter=8, row_block=64,
                       hash_dim=8)
    assert model.centroids.shape == (3, 8)
    # score rows the way the docstring prescribes: hash them identically
    # (to_dense sums the collision duplicates — shipped path)
    from rabit_tpu.learn.data import SparseMat
    hidx, hval = hash_features(data.findex, data.fvalue, 8)
    Xh = SparseMat(indptr=data.indptr, findex=hidx, fvalue=hval,
                   labels=data.labels, feat_dim=8).to_dense()
    cn = model.centroids / (np.linalg.norm(
        model.centroids, axis=1, keepdims=True) + 1e-12)
    xn = Xh / (np.linalg.norm(Xh, axis=1, keepdims=True) + 1e-12)
    assert (xn @ cn.T).max(axis=1).mean() > 0.9


def test_dense16_staging_matches_f32(empty_engine):
    """The half-width dense staging tier (compute_dtype="bfloat16" with
    a shard too big for the exact f32 blocks) must produce the same
    stats as the f32 tier within bf16 rounding, including the padded
    tail rows the 16384-row tile introduces."""
    from rabit_tpu.learn import kmeans

    data, X = _blob_data(n=256, d=16)
    idx, val, _, valid = data.to_ell(pad_index=16, row_block=64)
    rng = np.random.default_rng(3)
    model = kmeans.KMeansModel(
        rng.standard_normal((4, 16)).astype(np.float32))

    exact = kmeans.prepare_shard(idx, val, valid, 16, row_block=64)
    assert exact[0] == "dense"
    ref = np.asarray(kmeans.shard_stats_device(model, exact))

    half = kmeans.prepare_shard(idx, val, valid, 16, row_block=64,
                                budget=0, compute_dtype="bfloat16")
    assert half[0] == "dense16"
    x, v16 = half[2]
    assert x.shape[0] % 16384 == 0 and str(x.dtype) == "bfloat16"
    # features staged at the lane-padded width so stats calls never
    # re-pad the array
    assert x.shape[1] == 128
    got = np.asarray(kmeans.shard_stats_device(model, half))
    np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)
    # padded tail must be inert: counts equal
    np.testing.assert_allclose(got[:, -1], ref[:, -1])

    # a row_block that does not divide the 16384 tile must still stage
    # (rows round to lcm(row_block, tile))
    idx3, val3, _, valid3 = data.to_ell(pad_index=16, row_block=96)
    odd = kmeans.prepare_shard(idx3, val3, valid3, 16, row_block=96,
                               budget=0, compute_dtype="bfloat16")
    assert odd[0] == "dense16"
    got3 = np.asarray(kmeans.shard_stats_device(model, odd))
    np.testing.assert_allclose(got3, ref, rtol=3e-2, atol=3e-2)


def test_kmeans_hash_dim_pinned_by_checkpoint(empty_engine, monkeypatch):
    """Resuming with a different hash_dim than the checkpoint was trained
    with must fail loudly (ADVICE r4): the feat_dim clamp would otherwise
    silently drop out-of-range hashed features."""
    import pytest

    import rabit_tpu
    from rabit_tpu.learn import kmeans
    from rabit_tpu.utils.checks import RabitError

    data, _X = _blob_data(n=64, d=16)
    trained = kmeans.run(data, 3, 2, hash_dim=8)
    assert trained.hash_dim == 8
    monkeypatch.setattr(rabit_tpu, "load_checkpoint",
                        lambda: (2, trained))
    with pytest.raises(RabitError, match="hash_dim"):
        kmeans.run(data, 3, 4, hash_dim=16)
    # the matching width resumes fine
    ok = kmeans.run(data, 3, 4, hash_dim=8)
    assert ok.hash_dim == 8 and ok.centroids.shape == (3, 8)


def test_dense16_staging_fully_padded_chunk(empty_engine, monkeypatch):
    """Regression (ADVICE r4): with row_block not dividing the 16384
    tile, rows pad to lcm(row_block, tile) and a whole staging chunk can
    start PAST the real row count.  That chunk must be skipped (the
    output is zero-initialized), not padded to a negative real-row
    count — the old code computed pad > rows and the jitted writer died
    at dense.reshape."""
    import math

    from rabit_tpu.learn import kmeans

    # shrink the chunk so the >n16-vs-chunk geometry is cheap to build:
    # lcm(96, 16384) = 49152; chunk = (16384 // 96) * 96 = 16320, so
    # chunk starts 65280 and 81600 land inside [n, n16) = [49162, 98304)
    monkeypatch.setattr(kmeans, "_STAGE_CHUNK_ROWS", 16384)
    rb, d = 96, 16
    n = math.lcm(rb, kmeans._DENSE16_ROW_TILE) + 10
    rng = np.random.default_rng(7)
    idx = rng.integers(0, d, size=(n, 1)).astype(np.int32)
    val = rng.standard_normal((n, 1)).astype(np.float32)
    valid = np.ones(n, np.float32)

    x, v16 = kmeans._stage_dense16(idx, val, valid, d, rb, "bfloat16")
    n16 = x.shape[0]
    assert n16 == 2 * math.lcm(rb, kmeans._DENSE16_ROW_TILE)
    v16 = np.asarray(v16)
    assert v16[:n].all() and not v16[n:].any()
    xh = np.asarray(x).astype(np.float32)
    # padded rows are inert zeros; real rows carry their single feature
    assert not xh[n:].any()
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.arange(n), idx[:, 0]), val[:, 0])
    np.testing.assert_allclose(xh[:n, :d], dense, rtol=2e-2, atol=2e-2)
