"""Tests for the mesh + in-program collective layer (8 virtual CPU devices).

Mirrors the reference's numeric self-verification style: every collective
result is checked against a locally computed expectation
(reference: test/model_recover.cc:29-70).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rabit_tpu.ops import ReduceOp
from rabit_tpu.parallel import (
    DATA_AXIS,
    allgather,
    allreduce,
    broadcast,
    local_data_slice,
    make_mesh,
    reduce_scatter,
    ring_allreduce,
    shard_collective,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV
    return make_mesh(devices=jax.devices()[:N_DEV])


def _per_rank(mesh, fn, x_global):
    """Run fn(shard) under shard_map over the dp axis."""
    wrapped = shard_collective(
        mesh, fn, in_specs=(P(DATA_AXIS, None),), out_specs=P(DATA_AXIS, None))
    sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    return np.asarray(wrapped(jax.device_put(x_global, sharding)))


def test_allreduce_sum(mesh):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((N_DEV, 32)).astype(np.float32)
    out = _per_rank(mesh, lambda s: allreduce(s, DATA_AXIS, ReduceOp.SUM), x)
    expect = np.tile(x.sum(axis=0), (N_DEV, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_allreduce_max_min(mesh):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((N_DEV, 17)).astype(np.float32)
    out = _per_rank(mesh, lambda s: allreduce(s, DATA_AXIS, ReduceOp.MAX), x)
    np.testing.assert_array_equal(out[0], x.max(axis=0))
    out = _per_rank(mesh, lambda s: allreduce(s, DATA_AXIS, ReduceOp.MIN), x)
    np.testing.assert_array_equal(out[3], x.min(axis=0))


def test_allreduce_bitor(mesh):
    x = (1 << np.arange(N_DEV, dtype=np.int32))[:, None] * np.ones(
        (N_DEV, 4), np.int32)
    out = _per_rank(mesh, lambda s: allreduce(s, DATA_AXIS, ReduceOp.BITOR), x)
    np.testing.assert_array_equal(out, np.full((N_DEV, 4), (1 << N_DEV) - 1))


def test_allreduce_prod(mesh):
    x = np.full((N_DEV, 3), 2.0, np.float32)
    out = _per_rank(mesh, lambda s: allreduce(s, DATA_AXIS, ReduceOp.PROD), x)
    np.testing.assert_allclose(out, np.full((N_DEV, 3), 2.0 ** N_DEV))


@pytest.mark.parametrize("root", [0, 3, N_DEV - 1])
def test_broadcast_any_root(mesh, root):
    """Any rank can be broadcast root (reference: src/allreduce_base.cc:500)."""
    x = np.arange(N_DEV * 8, dtype=np.float32).reshape(N_DEV, 8)
    out = _per_rank(mesh, lambda s: broadcast(s, DATA_AXIS, root), x)
    np.testing.assert_array_equal(out, np.tile(x[root], (N_DEV, 1)))


def test_broadcast_int64_exact(mesh):
    """64-bit payloads broadcast exactly (no int32 truncation) when the
    user has x64 enabled (JAX's default mode downcasts at ingest)."""
    big = np.int64(1) << 40
    x = (np.arange(N_DEV, dtype=np.int64) * big).reshape(N_DEV, 1)
    with jax.enable_x64():
        out = _per_rank(mesh, lambda s: broadcast(s, DATA_AXIS, 3), x)
    np.testing.assert_array_equal(
        np.asarray(out, np.int64), np.full((N_DEV, 1), 3 * big, np.int64))


def test_broadcast_invalid_root_raises(mesh):
    x = np.zeros((N_DEV, 1), np.float32)
    with pytest.raises(ValueError, match="root"):
        _per_rank(mesh, lambda s: broadcast(s, DATA_AXIS, N_DEV), x)


def test_broadcast_int(mesh):
    x = np.arange(N_DEV, dtype=np.int32).reshape(N_DEV, 1) + 100
    out = _per_rank(mesh, lambda s: broadcast(s, DATA_AXIS, 5), x)
    np.testing.assert_array_equal(out, np.full((N_DEV, 1), 105, np.int32))


def test_allgather(mesh):
    x = np.arange(N_DEV * 2, dtype=np.float32).reshape(N_DEV, 2)
    out = _per_rank(
        mesh, lambda s: allgather(s, DATA_AXIS, axis=0, tiled=True), x)
    # every rank's shard is the full gathered matrix
    np.testing.assert_array_equal(out[:N_DEV], x)


def test_reduce_scatter(mesh):
    x = np.ones((N_DEV, N_DEV), np.float32)
    out = _per_rank(mesh, lambda s: reduce_scatter(s, DATA_AXIS, axis=1), x)
    # each rank ends with its 1-wide column slice of the sum
    np.testing.assert_array_equal(out, np.full((N_DEV, 1), N_DEV, np.float32))


@pytest.mark.parametrize("size", [1, 7, 64, 1000])
@pytest.mark.parametrize("op", [ReduceOp.SUM, ReduceOp.MAX])
def test_ring_allreduce_matches_psum(mesh, size, op):
    rng = np.random.default_rng(size)
    x = rng.standard_normal((N_DEV, size)).astype(np.float32)
    out = _per_rank(mesh, lambda s: ring_allreduce(s[0], DATA_AXIS, op)[None],
                    x[:, None, :].reshape(N_DEV, size))
    expect = x.sum(axis=0) if op == ReduceOp.SUM else x.max(axis=0)
    np.testing.assert_allclose(
        out, np.tile(expect, (N_DEV, 1)), rtol=1e-4, atol=1e-5)


def test_local_data_slice():
    parts = [local_data_slice(r, 3, 10) for r in range(3)]
    covered = sum((list(range(s.start, s.stop)) for s in parts), [])
    assert covered == list(range(10))
    assert max(s.stop - s.start for s in parts) - min(
        s.stop - s.start for s in parts) <= 1


def test_make_mesh_validates():
    with pytest.raises(ValueError):
        make_mesh(axis_sizes=(3,), devices=jax.devices()[:N_DEV])
