"""Smoke test for the speed_test benchmark worker (tiny sizes)."""
import sys


def test_speed_test_single_process(empty_engine):
    from rabit_tpu.tools.speed_test import run

    results = run(ndata=1000, nrep=3)
    assert set(results) == {"allreduce_max", "allreduce_sum", "broadcast"}
    for r in results.values():
        assert r["sec_mean"] >= 0.0
        assert r["mbps"] > 0.0


def test_speed_test_distributed(native_lib):
    from rabit_tpu.tracker.launch_local import launch

    code = launch(2, [sys.executable, "-m", "rabit_tpu.tools.speed_test",
                      "1000", "3"],
                  extra_env={"RABIT_ENGINE": "native"})
    assert code == 0
