"""Serving-plane tests (ISSUE 15, doc/serving.md).

Covers the overload-protection contract end to end:

* wire protocol round trips (predict/reply frames, every typed status,
  the ctrl channel);
* bounded admission + the DETERMINISTIC shed policy (same arrivals
  against the same gate state → the same verdicts, bit-for-bit);
* deadline budgets propagated through batch formation — an expired
  request is shed *before* compute, never predicted;
* micro-batch formation (batch_max cap, latency-budget flush);
* the committed-model convention: batched predict is bitwise
  batch-independent (the invariant the loadgen verifier — and the
  "zero wrong answers" soak criterion — stand on), atomic version
  swap, store fallback past a garbage blob;
* a standalone serving rank end to end over real sockets: OK replies
  with version tags, typed Overloaded with retry-after, typed Timeout,
  ctrl stats/health, drain choreography (endpoint unpublished, queued
  work answered DRAINING);
* the loadgen smoke (``--once``) and the accounting identity
  (offered == ok + shed + timeout + error);
* serve SLO series on the tracker exposition
  (``rabit_serve_requests_total{status=...}``, queue-depth gauge,
  latency percentile gauges) and the ``rabit_top`` serving row;
* the slow full gate: ``tools/soak.py --serve``.
"""
import io
import json
import socket
import threading
import time

import numpy as np
import pytest

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu import serve as S
from rabit_tpu.serve import protocol as SP
from rabit_tpu.serve.batching import AdmissionGate, QueuedRequest
from rabit_tpu.utils.serial import serialize_model

pytestmark = pytest.mark.serve


# ------------------------------------------------------------- helpers
def _make_store(path, versions=(1,), dim=8, seed=0):
    store = ckpt_mod.CheckpointStore(str(path), rank=0)
    weights = {}
    rng = np.random.default_rng(seed)
    for v in versions:
        w = rng.standard_normal(dim)
        store.persist(v, 1, serialize_model({"w": w}))
        weights[v] = w
    return store, weights


def _start_rank(model_dir, **kw):
    kw.setdefault("batch_wait_ms", 2)
    rank = S.ServeRank(str(model_dir), **kw)
    rank.start()
    return rank


def _request(rank, features, req_id=1, deadline_ms=0, sock=None):
    own = sock is None
    if own:
        sock = socket.create_connection((rank.host, rank.port),
                                        timeout=10)
    SP.PredictRequest(req_id, deadline_ms,
                      np.asarray(features, np.float32)).send(sock)
    reply = SP.PredictReply.recv(sock)
    if own:
        sock.close()
    return reply


# ------------------------------------------------------- wire protocol
def test_protocol_round_trip_all_statuses():
    a, b = socket.socketpair()
    try:
        SP.PredictRequest(42, 250,
                          np.arange(3, dtype=np.float32)).send(a)
        import rabit_tpu.tracker.protocol as P

        assert P.recv_u32(b) == SP.MAGIC_PREDICT
        req = SP.PredictRequest.recv_tail(b)
        assert (req.req_id, req.deadline_ms) == (42, 250)
        np.testing.assert_array_equal(
            req.features, np.arange(3, dtype=np.float32))

        for status, preds in [
                (SP.STATUS_OK, np.array([1.5, -2.25])),
                (SP.STATUS_SHED, None), (SP.STATUS_TIMEOUT, None),
                (SP.STATUS_ERROR, None), (SP.STATUS_DRAINING, None)]:
            SP.PredictReply(status, 42, model_version=7,
                            retry_after_ms=12, reason="why",
                            predictions=preds).send(b)
            got = SP.PredictReply.recv(a)
            assert (got.status, got.req_id, got.model_version,
                    got.retry_after_ms, got.reason) \
                == (status, 42, 7, 12, "why")
            if preds is None:
                assert got.predictions is None
            else:
                np.testing.assert_array_equal(got.predictions, preds)
    finally:
        a.close()
        b.close()


def test_protocol_feature_cap_is_typed():
    a, b = socket.socketpair()
    try:
        import struct

        a.sendall(struct.pack("<IIII", SP.MAGIC_PREDICT, 1, 0,
                              SP.MAX_FEATURES + 1))
        import rabit_tpu.tracker.protocol as P

        P.recv_u32(b)
        with pytest.raises(SP.ServeProtocolError):
            SP.PredictRequest.recv_tail(b)
    finally:
        a.close()
        b.close()


# --------------------------------------------- admission + shed policy
def _arrivals(gate, specs):
    """Drive one arrival sequence; returns the verdict list."""
    out = []
    for i, (t, deadline) in enumerate(specs):
        req = QueuedRequest(req_id=i, features=np.zeros(1, np.float32),
                            arrival=t, deadline=deadline)
        out.append(gate.submit(req)[0])
    return out


def test_admission_bounded_and_shed_typed():
    gate = AdmissionGate(queue_max=4, batch_max=2, batch_wait_ms=1000)
    for i in range(4):
        verdict, retry = gate.submit(QueuedRequest(
            i, np.zeros(1, np.float32), arrival=float(i),
            deadline=None))
        assert verdict == "admitted" and retry == 0
    verdict, retry = gate.submit(QueuedRequest(
        9, np.zeros(1, np.float32), arrival=9.0, deadline=None))
    assert verdict == "shed_queue_full"
    assert retry >= 1            # the retry-after drain estimate
    assert gate.depth() == 4     # the queue never grew past the bound
    assert gate.stats.shed_queue_full == 1


def test_admission_deadline_doomed_shed_on_arrival():
    gate = AdmissionGate(queue_max=100, batch_max=1, batch_wait_ms=0,
                         service_time_init_ms=50.0)
    # 10 queued batches ahead -> ~0.5 s wait; a 10 ms budget is doomed.
    for i in range(10):
        gate.submit(QueuedRequest(i, np.zeros(1, np.float32),
                                  arrival=0.0, deadline=None))
    verdict, retry = gate.submit(QueuedRequest(
        99, np.zeros(1, np.float32), arrival=0.0,
        deadline=0.010))
    assert verdict == "shed_deadline" and retry >= 1
    # A generous budget is admitted through the same state.
    verdict, _ = gate.submit(QueuedRequest(
        100, np.zeros(1, np.float32), arrival=0.0,
        deadline=10.0))
    assert verdict == "admitted"


def test_submit_racing_drain_gets_typed_verdict():
    """Review-driven: a submit that loses the race against drain()
    must get the 'draining' verdict — landing in the already-flushed
    queue would leave the client waiting on a reply nobody will ever
    send."""
    gate = AdmissionGate(queue_max=8, batch_max=2, batch_wait_ms=1)
    gate.submit(QueuedRequest(1, np.zeros(1, np.float32),
                              arrival=0.0, deadline=None))
    flushed = gate.drain()
    assert [r.req_id for r in flushed] == [1]
    verdict, retry = gate.submit(QueuedRequest(
        2, np.zeros(1, np.float32), arrival=0.0, deadline=None))
    assert verdict == "draining" and retry == 0
    assert gate.depth() == 0


def test_shed_policy_is_deterministic():
    """The chaos-composition contract: replaying one arrival sequence
    against a fresh gate replays the shed set exactly."""
    specs = [(float(i) * 0.001, None if i % 3 else 0.001 * i + 0.005)
             for i in range(40)]

    def play():
        gate = AdmissionGate(queue_max=8, batch_max=4,
                             batch_wait_ms=1000,
                             service_time_init_ms=20.0)
        return _arrivals(gate, specs)
    assert play() == play()


# ------------------------------------------------------- micro-batcher
def test_batcher_sheds_expired_before_compute():
    gate = AdmissionGate(queue_max=16, batch_max=8, batch_wait_ms=1)
    now = time.monotonic()
    # Admitted with a live 50 ms budget (the wait estimate is well
    # under it)...
    for i in range(3):
        verdict, _ = gate.submit(QueuedRequest(
            i, np.zeros(1, np.float32), arrival=now,
            deadline=now + 0.05))
        assert verdict == "admitted"
    gate.submit(QueuedRequest(7, np.zeros(1, np.float32),
                              arrival=now, deadline=now + 60))
    # ...then the budget dies while they sit in the queue.
    time.sleep(0.2)
    batch, expired = gate.take_batch(poll_sec=0.2)
    assert [r.req_id for r in batch] == [7]
    assert sorted(r.req_id for r in expired) == [0, 1, 2]
    assert all(r.shed == "timeout" for r in expired)
    assert gate.stats.timed_out == 3


def test_batch_formation_max_and_wait():
    gate = AdmissionGate(queue_max=64, batch_max=4, batch_wait_ms=30)
    now = time.monotonic()
    for i in range(10):
        gate.submit(QueuedRequest(i, np.zeros(1, np.float32),
                                  arrival=now, deadline=None))
    t0 = time.monotonic()
    batch, expired = gate.take_batch()
    assert len(batch) == 4 and not expired   # capped at batch_max
    assert time.monotonic() - t0 < 0.2       # full batch: no wait
    batch2, _ = gate.take_batch()
    assert [r.req_id for r in batch2] == [4, 5, 6, 7]


# ----------------------------------------------------- model contract
def test_predict_bitwise_batch_independent():
    """The loadgen verifier's foundation: a row's prediction is the
    same 8 bytes whether it rode a batch of 1 or 64."""
    rng = np.random.default_rng(3)
    model = S.ServedModel(1, rng.standard_normal(19))
    X = rng.standard_normal((64, 19)).astype(np.float32)
    full = model.predict(X)
    for i in (0, 17, 63):
        assert model.predict(X[i]) [0] == full[i]
        assert S.predict_row(model.weights, X[i]) == full[i]
    np.testing.assert_array_equal(model.predict(X[:5]), full[:5])


def test_model_slot_atomic_swap_and_fallback(tmp_path):
    store, weights = _make_store(tmp_path, versions=(1, 2))
    slot = S.ModelSlot()
    assert slot.load_from_store(store)
    assert slot.version == 2
    # an older install is refused (old version keeps serving)
    assert not slot.install(S.ServedModel(1, weights[1]))
    assert slot.version == 2
    # a newer version that does not follow the serving convention
    # falls back — the slot never swaps to garbage
    store.persist(3, 1, serialize_model({"not_w": 1}))
    assert not slot.load_from_store(store)
    assert slot.version == 2
    # a valid newer version swaps atomically
    w4 = np.ones(8)
    store.persist(4, 1, serialize_model({"w": w4}))
    assert slot.load_from_store(store)
    assert slot.version == 4
    np.testing.assert_array_equal(slot.get().weights, w4)


# ------------------------------------------- standalone rank, sockets
def test_serve_rank_ok_reply_verified(tmp_path):
    store, weights = _make_store(tmp_path / "m", versions=(1,))
    rank = _start_rank(tmp_path / "m")
    try:
        x = np.arange(8, dtype=np.float32)
        reply = _request(rank, x)
        assert reply.status == SP.STATUS_OK
        assert reply.model_version == 1
        assert reply.predictions[0] == S.predict_row(weights[1], x)
    finally:
        rank.stop()


def test_serve_rank_overload_typed_shed(tmp_path):
    """A saturated rank answers FAST with the typed Overloaded reply +
    retry-after instead of queueing into a blown deadline."""
    _make_store(tmp_path / "m")
    rank = _start_rank(tmp_path / "m", queue_max=2, batch_max=1,
                       batch_wait_ms=0, slow_ms=200)
    try:
        socks = [socket.create_connection((rank.host, rank.port),
                                          timeout=10)
                 for _ in range(8)]
        for i, s in enumerate(socks):
            SP.PredictRequest(i, 0,
                              np.zeros(8, np.float32)).send(s)
        statuses = []
        for s in socks:
            s.settimeout(10)
            r = SP.PredictReply.recv(s)
            statuses.append(r.status)
            if r.status == SP.STATUS_SHED:
                assert r.retry_after_ms >= 1
                assert "overloaded" in r.reason
        assert SP.STATUS_SHED in statuses
        assert SP.STATUS_OK in statuses
        for s in socks:
            s.close()
    finally:
        rank.stop()


def test_serve_rank_deadline_timeout_typed(tmp_path):
    """A queued request whose budget expires is answered with the
    typed Timeout and NEVER predicted (shed-before-compute)."""
    _make_store(tmp_path / "m")
    rank = _start_rank(tmp_path / "m", batch_max=1, batch_wait_ms=0,
                       slow_ms=300, queue_max=16)
    try:
        s1 = socket.create_connection((rank.host, rank.port),
                                      timeout=10)
        s2 = socket.create_connection((rank.host, rank.port),
                                      timeout=10)
        # First request occupies the batcher for ~300 ms; the second's
        # 50 ms budget dies in the queue.
        SP.PredictRequest(1, 0, np.zeros(8, np.float32)).send(s1)
        time.sleep(0.05)
        SP.PredictRequest(2, 50, np.zeros(8, np.float32)).send(s2)
        s2.settimeout(10)
        r2 = SP.PredictReply.recv(s2)
        assert r2.status == SP.STATUS_TIMEOUT
        assert r2.predictions is None
        s1.settimeout(10)
        assert SP.PredictReply.recv(s1).status == SP.STATUS_OK
        st = rank.stats()
        assert st["timed_out"] == 1
        s1.close()
        s2.close()
    finally:
        rank.stop()


def test_serve_rank_ctrl_and_drain(tmp_path):
    _make_store(tmp_path / "m")
    eps = tmp_path / "eps"
    rank = _start_rank(tmp_path / "m", endpoints_dir=str(eps),
                       task_id="sA")
    try:
        assert json.loads((eps / "sA.json").read_text())["port"] \
            == rank.port
        with socket.create_connection((rank.host, rank.port),
                                      timeout=10) as s:
            st = json.loads(SP.send_ctrl(s, SP.CTRL_STATS))
            assert st["model_version"] == 1 and st["health"] == "ok"
            assert SP.send_ctrl(s, SP.CTRL_HEALTH) == "ok"
            assert SP.send_ctrl(s, SP.CTRL_DRAIN) == "ok"
        # the drain choreography runs on the conn thread after the ack
        deadline = time.monotonic() + 5
        while not rank.drained and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rank.drained
        assert not (eps / "sA.json").exists()  # unpublished
        # post-drain traffic gets the typed DRAINING status on a
        # pre-existing connection; fresh connects are refused.
    finally:
        rank.stop()


def test_serve_rank_version_rollover_atomic(tmp_path):
    store, weights = _make_store(tmp_path / "m", versions=(1,))
    rank = _start_rank(tmp_path / "m")
    try:
        x = np.ones(8, dtype=np.float32)
        assert _request(rank, x).model_version == 1
        w2 = np.full(8, 2.5)
        store.persist(2, 1, serialize_model({"w": w2}))
        assert rank.refresh_model()
        reply = _request(rank, x)
        assert reply.model_version == 2
        assert reply.predictions[0] == S.predict_row(w2, x)
    finally:
        rank.stop()


def test_newest_loadable_version_skips_torn_blob(tmp_path):
    """Review-driven: the fleet agreement round advertises the newest
    version that VALIDATES — a trainer killed mid-persist (torn
    newest blob) must not wedge rollover past the valid version right
    under it."""
    store, _w = _make_store(tmp_path / "m", versions=(1, 2))
    rank = S.ServeRank(str(tmp_path / "m"))
    try:
        rank.slot.load_from_store(rank.store)
        assert rank.newest_loadable_version() == 2
        # a torn v3: valid blob name, corrupt bytes
        (tmp_path / "m" / "v00000003.r0.ckpt").write_bytes(b"torn!")
        assert rank.store.versions()[0] == 3
        assert rank.newest_loadable_version() == 2
        # the torn blob replaced by a valid persist is picked up
        store.persist(3, 1, serialize_model({"w": np.ones(8)}))
        assert rank.newest_loadable_version() == 3
    finally:
        rank.stop()


# ------------------------------------------------------------ loadgen
def test_loadgen_once_smoke(tmp_path):
    """The fast-tier smoke the CI satellite asks for: one request
    through the real stack, bitwise-verified."""
    from rabit_tpu.tools.loadgen import run_once

    _make_store(tmp_path / "m", dim=16)
    eps = tmp_path / "eps"
    rank = _start_rank(tmp_path / "m", endpoints_dir=str(eps),
                       task_id="s1")
    try:
        assert run_once(str(eps), None, 16,
                        str(tmp_path / "m")) == 0
    finally:
        rank.stop()


def test_loadgen_accounting_identity(tmp_path):
    """offered == ok + shed + timeout + error, exactly, with some of
    every outcome in play (tiny queue + big slow pad forces sheds)."""
    from rabit_tpu.tools.loadgen import run_load

    _make_store(tmp_path / "m", dim=16)
    eps = tmp_path / "eps"
    rank = _start_rank(tmp_path / "m", endpoints_dir=str(eps),
                       task_id="s1", queue_max=4, batch_max=2,
                       slow_ms=30)
    try:
        rep = run_load(str(eps), None, rate=200, duration=2,
                       deadline_ms=200, dim=16,
                       verify_dir=str(tmp_path / "m"), outstanding=32)
        assert rep["accounting_ok"], rep
        assert rep["offered"] == rep["ok"] + rep["shed"] \
            + rep["timeout"] + rep["error"]
        assert rep["wrong"] == 0
        assert rep["shed"] > 0 and rep["retry_after_seen"] > 0
        assert rep["ok"] > 0
    finally:
        rank.stop()


# ------------------------------------------------ SLOs on the obs plane
def test_serve_slo_series_on_tracker_exposition():
    """serve.requests.* counters render as ONE labeled Prometheus
    series (rabit_serve_requests_total{status=...}) plus queue-depth
    and latency-percentile gauges; /status carries the per-rank serve
    section the dashboard reads."""
    import collections
    import threading as _threading

    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker.__new__(Tracker)
    job = t._default_job()
    job.touched = True
    t._svc_lock = _threading.Lock()
    t._svc_counters = collections.Counter()
    job._live.ingest(0, 1.0, {
        "rank": 0,
        "counters": {"serve.requests.ok": 90, "serve.requests.shed": 7,
                     "serve.requests.timeout": 2, "serve.batches": 30},
        "gauges": {"serve.queue_depth": 3, "serve.model_version": 2,
                   "serve.latency.seconds.p50": 0.012,
                   "serve.latency.seconds.p99": 0.08}})
    text = t._render_metrics()
    assert ('rabit_serve_requests_total{job="default",rank="0",'
            'status="ok"} 90') in text
    assert ('rabit_serve_requests_total{job="default",rank="0",'
            'status="shed"} 7') in text
    assert "# TYPE rabit_serve_requests_total counter" in text
    assert 'rabit_serve_queue_depth{job="default",rank="0"} 3' in text
    assert "rabit_serve_latency_seconds_p99" in text
    # the split counters never double-render under their raw names
    assert "rabit_serve_requests_ok" not in text
    st = job._live.report()
    serve = st["0"]["serve"]
    assert serve["requests"] == {"ok": 90, "shed": 7, "timeout": 2}
    assert serve["queue_depth"] == 3 and serve["model_version"] == 2


def test_rabit_top_renders_serving_row():
    from rabit_tpu.tools.rabit_top import render

    live = {"0": {"frames": 1, "last_ts": 1.0, "engine": "x", "ops": 0,
                  "bytes": 0, "window": [],
                  "serve": {"requests": {"ok": 50, "shed": 3},
                            "batches": 9, "queue_depth": 4,
                            "model_version": 2,
                            "latency_p50_sec": 0.01,
                            "latency_p99_sec": 0.05}}}
    status = {"ts": 2.0,
              "service": {"jobs_active": ["serve"], "counters": {}},
              "jobs": {"serve": {"world": 1, "epoch": 0,
                                 "committed_version": 0, "done": False,
                                 "members": ["s1"], "live": live,
                                 "liveness": {},
                                 "straggler_scores": {}}}}
    buf = io.StringIO()
    render(status, None, out=buf)
    out = buf.getvalue()
    assert "serving: v=2 ok=50 shed=3" in out
    assert "q=4" in out and "p99=50.0ms" in out


# ------------------------------------------------------- the slow gate
@pytest.mark.slow
def test_serve_soak_gate():
    """The headline gate: steady → rollover → 2x spike (typed sheds,
    p99 bounded) → rank SIGKILL (elastic recovery, bit-consistent
    answers) → train-while-serving co-tenant bit-exactness."""
    from rabit_tpu.tools.soak import main as soak_main

    assert soak_main(["--serve", "--rounds", "1"]) == 0
