"""Test-only mpi4py stub: just enough of the mpi4py surface to execute
rabit_tpu.engine.mpi's body in CI, where no real MPI runtime exists.

The real mpi4py is not bundled in the TPU image, so without this the MPI
engine (reference analogue: src/engine_mpi.cc:20-205) would never run.
The stub implements COMM_WORLD over plain TCP with a star topology
through rank 0 (rendezvous via MPI_STUB_RANK/SIZE/PORT env vars) — a
correctness harness, not a performance transport.  It lives under
tests/ and is injected via PYTHONPATH by tests/test_mpi_engine.py only.
"""
from . import MPI  # noqa: F401
