"""The MPI submodule of the test-only mpi4py stub (see package docstring).

Implements the subset rabit_tpu.engine.mpi uses: COMM_WORLD with
Get_rank/Get_size/Allreduce(IN_PLACE)/bcast/Allgather/Barrier, IN_PLACE,
and the numeric reduction ops.  Every collective routes through rank 0
(gather → fold/serve → scatter) over length-prefixed TCP frames.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import time

import numpy as np

IN_PLACE = object()

# Reduction ops carry the numpy fold used by rank 0.
class _Op:
    def __init__(self, name, fold):
        self.name = name
        self.fold = fold

    def __repr__(self):  # pragma: no cover
        return f"<stub MPI.{self.name}>"


MAX = _Op("MAX", lambda d, s: np.maximum(d, s, out=d))
MIN = _Op("MIN", lambda d, s: np.minimum(d, s, out=d))
SUM = _Op("SUM", lambda d, s: np.add(d, s, out=d))
PROD = _Op("PROD", lambda d, s: np.multiply(d, s, out=d))
BOR = _Op("BOR", lambda d, s: np.bitwise_or(d, s, out=d))
BAND = _Op("BAND", lambda d, s: np.bitwise_and(d, s, out=d))
BXOR = _Op("BXOR", lambda d, s: np.bitwise_xor(d, s, out=d))


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        part = sock.recv(8 - len(hdr))
        if not part:
            raise ConnectionError("stub MPI peer closed")
        hdr += part
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray(n)
    got = 0
    while got < n:
        k = sock.recv_into(memoryview(buf)[got:], n - got)
        if k == 0:
            raise ConnectionError("stub MPI peer closed")
        got += k
    return bytes(buf)


class _Comm:
    """COMM_WORLD: star topology through rank 0, lazily connected."""

    def __init__(self) -> None:
        self._rank = int(os.environ.get("MPI_STUB_RANK", 0))
        self._size = int(os.environ.get("MPI_STUB_SIZE", 1))
        self._port = int(os.environ.get("MPI_STUB_PORT", 0))
        self._links: dict[int, socket.socket] = {}  # rank 0: peer -> sock
        self._up: socket.socket | None = None  # non-root: link to rank 0
        self._wired = False

    def _wire(self) -> None:
        if self._wired or self._size == 1:
            self._wired = True
            return
        if self._rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", self._port))
            srv.listen(self._size)
            for _ in range(self._size - 1):
                s, _addr = srv.accept()
                peer = struct.unpack("<I", _recv_frame(s))[0]
                self._links[peer] = s
            srv.close()
        else:
            for _ in range(100):
                try:
                    self._up = socket.create_connection(
                        ("127.0.0.1", self._port), timeout=30)
                    break
                except OSError:
                    time.sleep(0.1)
            else:
                raise ConnectionError("stub MPI: rank 0 never listened")
            _send_frame(self._up, struct.pack("<I", self._rank))
        self._wired = True

    def Get_rank(self) -> int:
        return self._rank

    def Get_size(self) -> int:
        return self._size

    # gather py-objects to rank 0, apply serve(list) there, scatter result
    def _through_root(self, obj, serve):
        self._wire()
        if self._size == 1:
            return serve([obj])
        if self._rank == 0:
            parts = [obj] + [None] * (self._size - 1)
            for peer, sock in self._links.items():
                parts[peer] = pickle.loads(_recv_frame(sock))
            out = serve(parts)
            blob = pickle.dumps(out)
            for sock in self._links.values():
                _send_frame(sock, blob)
            return out
        _send_frame(self._up, pickle.dumps(obj))
        return pickle.loads(_recv_frame(self._up))

    def Allreduce(self, sendbuf, recvbuf, op=SUM):
        assert sendbuf is IN_PLACE, "stub supports IN_PLACE only"
        folded = self._through_root(
            np.ascontiguousarray(recvbuf),
            lambda parts: _fold(parts, op))
        recvbuf[...] = folded
        return recvbuf

    def bcast(self, obj, root: int = 0):
        return self._through_root(
            obj, lambda parts: parts[root])

    def Allgather(self, sendbuf, recvbuf):
        parts = self._through_root(
            np.ascontiguousarray(sendbuf), lambda ps: np.stack(ps))
        recvbuf[...] = parts
        return recvbuf

    def Barrier(self) -> None:
        self._through_root(None, lambda parts: None)


def _fold(parts, op):
    acc = np.array(parts[0], copy=True)
    for p in parts[1:]:
        op.fold(acc, p)
    return acc


COMM_WORLD = _Comm()
