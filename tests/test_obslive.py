"""Live telemetry plane tests (streaming export, scrape endpoint,
cross-rank spans + straggler attribution — doc/observability.md "Live
telemetry").

Fast unit coverage for the span merge on synthetic skewed timelines,
the delta exporter / live-table fold, the Prometheus exposition
renderer and the event-trace drop counter — plus distributed gates: a
mid-run ``GET /metrics``/``GET /status`` scrape against a running job
(with a deliberately slowed rank earning a straggler verdict and its
obs_report table), a scrape-under-chaos round that must stay
consistent, and multi-job label scoping with no cross-tenant bleed.
"""
import json
import sys
import threading
import time
import urllib.request

import pytest

from rabit_tpu import obs

pytestmark = pytest.mark.obslive


def _span(seq, t0, t1, epoch=0, version=0, kind="allreduce",
          sched="tree", nbytes=1024):
    """One wire-layout span (obs.span.SPAN_FIELDS)."""
    return [seq, epoch, version, kind, sched, nbytes, t0, t1]


# ------------------------------------------------------------ span merge
def test_merge_group_skew_and_lateness():
    res = obs.merge_group({0: (10.0, 10.5), 1: (10.4, 10.55),
                           2: (10.01, 10.52)})
    assert res["latest_rank"] == 1
    assert res["skew"] == pytest.approx(0.4)
    assert res["lateness"][0] == 0.0
    assert res["lateness"][1] == pytest.approx(0.4)
    # the true op cost is the LAST arriver's own duration
    assert res["op_sec"] == pytest.approx(0.15)


def test_span_merger_flags_the_late_rank():
    sm = obs.SpanMerger(min_ops=4)
    for i in range(8):
        sm.add(0, [_span(i, 100.0 + i, 100.01 + i)], world=3)
        sm.add(1, [_span(i, 100.5 + i, 100.51 + i)], world=3)
        sm.add(2, [_span(i, 100.02 + i, 100.52 + i)], world=3)
    verdicts = sm.straggler_verdicts(factor=3.0, min_sec=0.05)
    assert [v[0] for v in verdicts] == [1]
    rank, score, late = verdicts[0]
    assert late == pytest.approx(0.5, rel=0.05)
    assert score > 3.0
    # healthy ranks stay unflagged and low-scored
    assert sm.score(0) < 1.0 and sm.score(2) < 3.0


def test_span_merger_min_sec_floor_suppresses_jitter():
    """Microsecond-scale scheduling jitter must not produce verdicts:
    the relative score is huge but the absolute lateness is tiny."""
    sm = obs.SpanMerger(min_ops=4)
    for i in range(8):
        sm.add(0, [_span(i, 100.0 + i, 100.0001 + i)], world=2)
        sm.add(1, [_span(i, 100.001 + i, 100.0011 + i)], world=2)
    assert sm.score(1) > 3.0  # relatively late every time...
    assert sm.straggler_verdicts(3.0, min_sec=0.05) == []  # ...but tiny


def test_span_merger_partial_and_malformed():
    """Groups missing ranks finalize on eviction without error (pairs
    still score), single-rank groups carry no signal, and malformed
    wire entries are skipped."""
    sm = obs.SpanMerger(max_pending=8, min_ops=1)
    sm.add(0, [["garbage"], None, 7, _span(0, 1.0, 1.1)], world=4)
    assert sm.merged_ops == 0
    # only two of four ranks ever report seqs 1..10: eviction merges
    # the pairs once the pending set overflows
    for i in range(1, 11):
        sm.add(0, [_span(i, 10.0 + i, 10.1 + i)], world=4)
        sm.add(1, [_span(i, 10.2 + i, 10.3 + i)], world=4)
    assert sm.merged_ops >= 2
    assert sm.score(1) > 0.0
    rep = sm.report()
    assert rep["sched"]["tree"]["count"] == sm.merged_ops
    assert rep["ranks"]["1"]["sched_lateness_sec"]["tree"] > 0


def test_span_merger_version_disambiguates_seqno():
    """The robust protocol resets seqno per version span: spans of
    (v1, seq 0) and (v2, seq 0) must form two groups, never one."""
    sm = obs.SpanMerger(min_ops=1)
    sm.add(0, [_span(0, 10.0, 10.1, version=1)], world=2)
    sm.add(1, [_span(0, 50.0, 50.1, version=2)], world=2)
    assert sm.merged_ops == 0  # different versions: no bogus merge
    sm.add(1, [_span(0, 10.01, 10.1, version=1)], world=2)
    assert sm.merged_ops == 1


# ------------------------------------------------- delta export + fold
def test_delta_exporter_counters_are_deltas():
    m = obs.Metrics()
    ex = obs.DeltaExporter(m)
    m.counter("op.allreduce.count").inc(3)
    m.gauge("g").set(1.5)
    m.histogram("hb.rtt.seconds").observe(0.01)
    f1 = ex.frame()
    assert f1["counters"] == {"op.allreduce.count": 3}
    assert f1["gauges"]["g"] == 1.5
    assert f1["gauges"]["hb.rtt.seconds.count"] == 1
    m.counter("op.allreduce.count").inc(2)
    f2 = ex.frame()
    assert f2["counters"] == {"op.allreduce.count": 2}
    assert ex.frame()["counters"] == {}  # idle: empty delta


def test_live_table_folds_deltas_and_bounds_window():
    lt = obs.LiveTable(window=4)
    for i in range(10):
        lt.ingest(0, 100.0 + i, {"counters": {"op.x.count": 1,
                                              "op.x.bytes": 10},
                                 "gauges": {"v": i}})
    rows = dict(lt.rows())
    assert rows[0]["counters"]["op.x.count"] == 10
    assert rows[0]["gauges"]["v"] == 9
    rep = lt.report()
    assert rep["0"]["frames"] == 10
    assert rep["0"]["ops"] == 10 and rep["0"]["bytes"] == 100
    assert len(rep["0"]["window"]) == 4  # bounded
    # non-numeric garbage from the wire is dropped, not raised
    lt.ingest(0, 111.0, {"counters": {"op.x.count": "NaNsense"},
                         "gauges": {"v": "x"}})
    assert dict(lt.rows())[0]["counters"]["op.x.count"] == 10


def test_prometheus_text_format():
    text = obs.prometheus_text(
        [("rabit_op_allreduce_count", {"job": "a", "rank": "0"}, 5),
         ("rabit_op_allreduce_count", {"job": "b", "rank": "0"}, 7.0),
         ("rabit_x", {"job": 'we"ird\nname'}, 1.5),
         ("rabit_bad", {}, float("nan"))],
        {"rabit_op_allreduce_count": "counter"})
    lines = text.splitlines()
    assert "# TYPE rabit_op_allreduce_count counter" in lines
    assert 'rabit_op_allreduce_count{job="a",rank="0"} 5' in lines
    assert 'rabit_op_allreduce_count{job="b",rank="0"} 7' in lines
    assert 'rabit_x{job="we\\"ird\\nname"} 1.5' in lines
    assert not any("rabit_bad" in ln and "nan" in ln for ln in lines)
    assert obs.prom_name("op.allreduce.count") == \
        "rabit_op_allreduce_count"
    assert obs.prom_name("9weird") == "rabit__9weird"


def test_event_trace_dropped_counter():
    tr = obs.EventTrace(capacity=4)
    for i in range(10):
        tr.emit("op", seqno=i)
    assert tr.dropped == 6
    m = obs.Metrics()
    obs.note_drops(m, tr)
    assert m.counter("obs.events_dropped").value == 6
    obs.note_drops(m, tr)  # idempotent
    assert m.counter("obs.events_dropped").value == 6


def test_obs_configure_flush(monkeypatch):
    monkeypatch.delenv("RABIT_OBS_FLUSH_SEC", raising=False)
    assert obs.configure({"rabit_obs": 1}).flush_sec == \
        obs.DEFAULT_FLUSH_SEC
    assert obs.configure({"rabit_obs_flush_sec": 0.5}).flush_sec == 0.5
    assert obs.configure({"rabit_obs_flush_sec": 0}).flush_sec == 0.0
    assert obs.configure({"rabit_obs_flush_sec": -3}).flush_sec == 0.0
    monkeypatch.setenv("RABIT_OBS_FLUSH_SEC", "1.25")
    assert obs.configure({}).flush_sec == 1.25


# -------------------------------------------------- scrape endpoint
def _get(port: int, path: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read().decode()


def test_scrape_multijob_label_scoping():
    """Two jobs streaming frames into one tracker: /metrics and /status
    must scope every series to its job — no cross-tenant bleed."""
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, obs_port=0)
    try:
        assert t.obs_port
        ja = t._admit("joba", 2)
        jb = t._admit("jobb", 2)
        ja._obs_frame_ingest("0", json.dumps(
            {"rank": 0, "counters": {"op.allreduce.count": 11},
             "gauges": {}}).encode())
        jb._obs_frame_ingest("0", json.dumps(
            {"rank": 0, "counters": {"op.allreduce.count": 22},
             "gauges": {}}).encode())
        jb._obs_frame_ingest("0", b"\xff not json")  # dropped, counted
        assert jb._obs_frames_bad == 1
        metrics = _get(t.obs_port, "/metrics")
        assert 'rabit_op_allreduce_count{job="joba",rank="0"} 11' \
            in metrics
        assert 'rabit_op_allreduce_count{job="jobb",rank="0"} 22' \
            in metrics
        # every op series carries a job label (scoping is structural)
        for ln in metrics.splitlines():
            if ln.startswith("rabit_op_") and not ln.startswith("#"):
                assert 'job="' in ln, ln
        status = json.loads(_get(t.obs_port, "/status"))
        assert set(status["jobs"]) == {"joba", "jobb"}
        assert status["jobs"]["joba"]["live"]["0"]["ops"] == 11
        assert status["jobs"]["jobb"]["live"]["0"]["ops"] == 22
        assert _get(t.obs_port, "/healthz").strip() == "ok"
        with pytest.raises(urllib.error.HTTPError):
            _get(t.obs_port, "/nope")
    finally:
        t.stop()
        t._close_all()


def test_rabit_top_once(capfd):
    """The terminal dashboard renders a /status snapshot (--once)."""
    from rabit_tpu.tools import rabit_top
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, obs_port=0)
    try:
        job = t._admit("dash", 2)
        job._obs_frame_ingest("0", json.dumps(
            {"rank": 0, "counters": {"op.allreduce.count": 5,
                                     "op.allreduce.bytes": 4096},
             "gauges": {}}).encode())
        assert rabit_top.main(["--port", str(t.obs_port), "--once"]) == 0
        out = capfd.readouterr().out
        assert "job dash" in out and "world=2" in out
        assert "5" in out  # the streamed op total renders
    finally:
        t.stop()
        t._close_all()
    # unreachable endpoint: --once exits 1, no traceback
    assert rabit_top.main(["--port", "1", "--once",
                           "--host", "127.0.0.1"]) == 1


# -------------------------------------------- distributed live gates
def _poll_scrape(port: int, hits: dict, deadline_sec: float = 90.0,
                 want_straggler: bool = False) -> None:
    """Background poller: record the first healthy /metrics + /status
    pair (and, optionally, the first straggler verdict)."""
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        try:
            m = _get(port, "/metrics", timeout=2)
            s = json.loads(_get(port, "/status", timeout=2))
        except (OSError, ValueError):
            time.sleep(0.1)
            continue
        if "rabit_op_allreduce_count" in m and "metrics" not in hits:
            hits["metrics"] = m
            hits["status"] = s
        if want_straggler:
            for job in (s.get("jobs") or {}).values():
                if job.get("stragglers"):
                    hits["straggler_status"] = s
                    return
        elif "metrics" in hits:
            return
        time.sleep(0.1)


def test_live_scrape_and_straggler_end_to_end(tmp_path):
    """A world-2 pyrobust job with rank 1 deliberately slowed: the
    mid-run scrape returns live per-rank op counters + heartbeat
    freshness, the tracker fires a straggler event for rank 1, and the
    final obs report carries the straggler table with per-schedule
    skew (rendered by obs_report without error)."""
    from rabit_tpu.tools import obs_report
    from rabit_tpu.tracker.launch_local import launch
    from rabit_tpu.utils.net import free_port

    port = free_port("127.0.0.1")
    hits: dict = {}
    poller = threading.Thread(target=_poll_scrape, args=(port, hits),
                              kwargs={"want_straggler": True},
                              daemon=True)
    poller.start()
    out = tmp_path / "out"
    out.mkdir()
    code = launch(2, [sys.executable, "tests/workers/cold_restart.py",
                      "300", "8"],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_OUT_DIR": str(out),
                             "RABIT_ITER_SLEEP": "0.05",
                             "RABIT_SLOW_RANK": "1",
                             "RABIT_SLOW_EXTRA": "0.3",
                             "RABIT_OBS_FLUSH_SEC": "0.2"},
                  obs_dir=str(tmp_path / "obs"), obs_port=port)
    assert code == 0
    poller.join(timeout=10)
    assert "metrics" in hits, "mid-run scrape never became healthy"
    metrics = hits["metrics"]
    assert 'rabit_op_allreduce_count{job="default",rank="0"}' in metrics
    assert "rabit_hb_last_seen_seconds" in metrics
    assert "rabit_job_world" in metrics
    # the straggler verdict fired mid-run and names the slowed rank
    assert "straggler_status" in hits, \
        "no straggler verdict while the job ran"
    job = hits["straggler_status"]["jobs"]["default"]
    assert "1" in job["stragglers"]
    # final report: straggler table + per-schedule latency, renderable
    report = json.loads(
        (tmp_path / "obs" / "obs_report.json").read_text())
    stragg = report["straggler"]
    assert 1 in stragg["straggling"]
    assert stragg["ranks"]["1"]["score"] > \
        stragg["ranks"]["0"]["score"]
    assert stragg["ranks"]["1"]["sched_lateness_sec"]
    assert report["sched_latency"]
    assert any(e.get("name") == "straggler" and e.get("rank") == 1
               for e in report["recovery_timeline"])
    assert obs_report.main([str(tmp_path / "obs")]) == 0


def test_scrape_under_chaos(tmp_path):
    """A seeded-chaos round must keep the scrape endpoint consistent:
    every mid-run GET answers 200 with parseable, job-labeled data —
    wire faults never 500 the exposition."""
    from rabit_tpu.tracker.launch_local import launch
    from rabit_tpu.utils.net import free_port

    port = free_port("127.0.0.1")
    results: dict = {"scrapes": 0, "bad": []}

    def hammer():
        end = time.monotonic() + 60
        while time.monotonic() < end and not results.get("stop"):
            try:
                m = _get(port, "/metrics", timeout=2)
                json.loads(_get(port, "/status", timeout=2))
            except OSError:
                time.sleep(0.1)
                continue
            except ValueError as e:
                results["bad"].append(f"unparseable /status: {e}")
                return
            results["scrapes"] += 1
            for ln in m.splitlines():
                if ln.startswith("rabit_op_") and 'job="' not in ln:
                    results["bad"].append(f"unlabeled op series: {ln}")
                    return
            time.sleep(0.05)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    out = tmp_path / "out"
    out.mkdir()
    code = launch(2, [sys.executable, "tests/workers/cold_restart.py",
                      "400", "6"],
                  extra_env={
                      "RABIT_ENGINE": "pyrobust",
                      "RABIT_OUT_DIR": str(out),
                      "RABIT_ITER_SLEEP": "0.05",
                      "RABIT_OBS": "1",
                      "RABIT_OBS_FLUSH_SEC": "0.2",
                      "RABIT_CHAOS": ("7:reset@io=0.002*2;"
                                      "partial@io=0.05*200;"
                                      "eintr@io=0.02*40;stallms=20;"
                                      "budget=256"),
                      "RABIT_TIMEOUT_SEC": "20",
                      "RABIT_BACKOFF_BASE_MS": "20"},
                  obs_port=port)
    results["stop"] = True
    t.join(timeout=10)
    assert code == 0
    assert not results["bad"], results["bad"]
    assert results["scrapes"] > 0, "scrape never reached the endpoint"


# --------------------------------------------- obs_report hardening
def test_obs_report_torn_inputs(tmp_path):
    """Torn shutdowns degrade to '(absent)' rows and skipped lines,
    never a traceback: a rank summary missing from the report, a
    truncated JSONL line, and a corrupt report file all render.
    (Capture-free on purpose — the renderers take an explicit ``out``
    stream, and the stderr notes ride redirect_stderr.)"""
    import contextlib
    import io
    import pathlib

    from rabit_tpu.tools import obs_report

    d = tmp_path / "obs"
    d.mkdir()
    report = {"job": "t", "world": 3, "ranks_reported": [0, 2],
              "ranks": {"0": {"metrics": {"counters": {"x": 1}}},
                        "2": {"metrics": {}}},
              "aggregate": {"obs.events_dropped":
                            {"min": 0, "mean": 1, "max": 2}},
              "recovery_timeline": [{"ts": 1.0, "name": "liveness",
                                     "phase": "alive", "task": "0"},
                                    "not-a-dict"]}
    (d / "obs_report.json").write_text(json.dumps(report))
    (d / "events.rank0.jsonl").write_text(
        json.dumps({"ts": 1.0, "name": "op", "rank": 0}) + "\n"
        + '{"ts": 2.0, "name": "op", "ra')  # torn mid-write
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        loaded, events = obs_report._load(pathlib.Path(d))
    assert "torn/corrupt" in err.getvalue()
    assert len(events) == 1  # the intact line survived
    buf = io.StringIO()
    obs_report.render_report(loaded, out=buf)
    text = buf.getvalue()
    assert "(absent)" in text and "rank 1" in text
    assert "WARNING" in text and "dropped" in text
    assert obs_report.main([str(d)]) == 0  # full CLI path: no traceback
    # corrupt report file: the events still render, exit 0
    (d / "obs_report.json").write_text("{corrupt json")
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        loaded, events = obs_report._load(pathlib.Path(d))
    assert loaded is None and "unreadable" in err.getvalue()
    assert obs_report.main([str(d)]) == 0
    # a corrupt report passed DIRECTLY (not a dir) exits 1 gracefully
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2")
    assert obs_report.main([str(bad)]) == 1
