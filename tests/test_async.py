"""Async collective handles, bucket fusion, wire dtype and the ring
ragged-size regression (doc/performance.md).

The contracts pinned here:

* async + bucketed results are BIT-identical to the blocking path on
  both socket engines (fusion preserves each member's reduction order);
* handles resolve in issue order and out-of-order ``wait()`` raises;
* pyrobust replays in-flight async/fused ops correctly under
  kill-points (each bucket is one seqno in the replay cache);
* a progress-thread link failure surfaces at ``wait()`` (LinkError),
  never as a bare thread traceback;
* ``rabit_wire_dtype=bf16`` halves wire bytes within the documented
  accuracy envelope and never touches non-eligible ops.
"""
import sys

import numpy as np
import pytest


def _launch(worker, world, extra_env=None, args=()):
    from rabit_tpu.tracker.launch_local import launch

    return launch(world, [sys.executable, f"tests/workers/{worker}.py",
                          *map(str, args)], extra_env=extra_env or {})


# ------------------------------------------------------------- unit layer
def test_resolved_handle_semantics():
    from rabit_tpu import CollectiveHandle

    h = CollectiveHandle.resolved(42)
    assert h.done() and h.wait() == 42
    assert h.wait() == 42  # idempotent
    h2 = CollectiveHandle()
    assert not h2.done()
    h2._fail(ValueError("boom"))
    with pytest.raises(ValueError, match="boom"):
        h2.wait()


def test_async_api_world1(empty_engine):
    import rabit_tpu

    a = np.arange(8, dtype=np.float32)
    h = rabit_tpu.allreduce_async(a, rabit_tpu.SUM)
    assert h.done() and h.wait() is a
    outs = rabit_tpu.allreduce_many(
        [np.ones(3, np.float32), np.full(2, 2.0, np.float32)])
    assert [o.tolist() for o in outs] == [[1, 1, 1], [2, 2]]
    g = rabit_tpu.allgather_async(np.arange(3, dtype=np.int32))
    assert g.wait().shape == (1, 3)


# -------------------------------------------------------- async semantics
@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
def test_async_bit_identical_to_blocking(engine):
    assert _launch("async_worker", 4, {"RABIT_ENGINE": engine},
                   ["parity"]) == 0


@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
def test_async_out_of_order_wait_raises(engine):
    assert _launch("async_worker", 3, {"RABIT_ENGINE": engine},
                   ["order"]) == 0


@pytest.mark.obs
def test_bucket_fusion_counters():
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_OBS": "1"}, ["fusion"]) == 0


def test_async_parity_with_sock_buf():
    """rabit_sock_buf applies at link wiring without changing results."""
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_SOCK_BUF": "256KB"},
                   ["parity"]) == 0


def test_async_parity_with_fusion_disabled():
    """rabit_bucket_bytes=0 turns fusion off; the async stream still
    resolves in order with blocking-identical bits."""
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_BUCKET_BYTES": "0"},
                   ["parity"]) == 0


@pytest.mark.perf
def test_async_overlap_smoke():
    """Fast overlap smoke for the perf suite: compute runs while the
    wire op is in flight, and the overlap histogram records it."""
    assert _launch("async_worker", 2, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_OBS": "1"}, ["overlap"]) == 0


# ----------------------------------------------------------- wire dtype
@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
def test_wire_bf16_accuracy_guard(engine):
    assert _launch("async_worker", 4, {"RABIT_ENGINE": engine,
                                       "RABIT_WIRE_DTYPE": "bf16"},
                   ["bf16"]) == 0


def test_wire_dtype_rejects_unknown(empty_engine):
    import rabit_tpu
    from rabit_tpu.engine.pysocket import PySocketEngine
    from rabit_tpu.utils import RabitError

    eng = PySocketEngine()
    with pytest.raises(RabitError, match="rabit_wire_dtype"):
        eng.init({"rabit_wire_dtype": "fp8", "rabit_tracker_uri": "x",
                  "rabit_tracker_port": 1})
    assert rabit_tpu.get_world_size() == 1


# ------------------------------------------------- ring ragged-size edge
@pytest.mark.parametrize("world", [4, 5])
def test_ring_allreduce_ragged_sizes(world):
    """Regression for the ring sub-chunk loop: payloads with
    len % world != 0 (including len < world, i.e. zero-length edge
    blocks) must reduce exactly under a tiny reduce-buffer budget."""
    assert _launch("ring_oddsize", world,
                   {"RABIT_ENGINE": "pysocket",
                    "RABIT_REDUCE_BUFFER": "128"}) == 0


# ------------------------------------------------------ replay under kill
@pytest.mark.recovery
def test_async_replay_no_faults():
    assert _launch("async_kill", 4, {"RABIT_ENGINE": "pyrobust"}) == 0


@pytest.mark.recovery
def test_async_replay_death_at_fused_bucket():
    # rank 1 dies at version 1 seq 0 — the fused bucket op; its restart
    # must be served the cached FUSED payload and split it back right.
    assert _launch("async_kill", 4, {"RABIT_ENGINE": "pyrobust",
                                     "RABIT_MOCK": "1,1,0,0"}) == 0


@pytest.mark.recovery
def test_async_replay_two_deaths():
    # deaths at the fused op of v1 and the solo async op of v2
    assert _launch("async_kill", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_MOCK": "2,1,0,0;1,2,1,0"}) == 0


@pytest.mark.recovery
def test_async_replay_death_at_checkpoint():
    ckpt = 1 << 20
    assert _launch("async_kill", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_MOCK": f"3,1,{ckpt},0"}) == 0
