"""Histogram builder tests: numeric parity with numpy and the
allreduce wire pattern on the empty engine."""
import numpy as np
import pytest

from rabit_tpu.learn import histogram


def _np_hist(bins, grad, hess, nbin):
    n, f = bins.shape
    out = np.zeros((f, nbin, 2), np.float64)
    for j in range(f):
        for b in range(nbin):
            m = bins[:, j] == b
            out[j, b, 0] = grad[m].sum()
            out[j, b, 1] = hess[m].sum()
    return out.astype(np.float32)


@pytest.mark.parametrize("n,f,nbin", [(1000, 5, 16), (513, 3, 7)])
def test_build_local_matches_numpy(n, f, nbin):
    rng = np.random.default_rng(0)
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.random(n).astype(np.float32)
    got = np.asarray(histogram.build_local(
        bins, grad, hess, nbin, row_block=256, feat_block=2))
    want = _np_hist(bins, grad, hess, nbin)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_quantize_bounds():
    rng = np.random.default_rng(1)
    vals = rng.standard_normal((500, 4)).astype(np.float32)
    bins, cuts = histogram.quantize(vals, 32)
    assert bins.min() >= 0 and bins.max() < 32
    assert cuts.shape == (4, 31)
    # roughly uniform occupancy from quantile cuts
    counts = np.bincount(bins[:, 0], minlength=32)
    assert counts.min() > 0


def test_build_allreduce_empty_engine(empty_engine):
    rng = np.random.default_rng(2)
    bins = rng.integers(0, 8, (300, 4)).astype(np.int32)
    grad = rng.standard_normal(300).astype(np.float32)
    hess = np.ones(300, np.float32)
    got = histogram.build_allreduce(bins, grad, hess, 8,
                                    row_block=128, feat_block=4)
    want = _np_hist(bins, grad, hess, 8)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)
    # hessian column of counts sums to n
    assert got[:, :, 1].sum() == pytest.approx(4 * 300)


def test_split_gain_prefers_clean_split():
    # two clusters: negative gradients in low bins, positive in high bins
    nbin = 8
    hist = np.zeros((1, nbin, 2), np.float32)
    hist[0, :4, 0] = -5.0
    hist[0, 4:, 0] = +5.0
    hist[0, :, 1] = 10.0
    gain = histogram.split_gain(hist)
    assert gain.shape == (1, nbin - 1)
    assert gain.argmax() == 3  # the boundary between the clusters
