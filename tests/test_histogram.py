"""Histogram builder tests: numeric parity with numpy and the
allreduce wire pattern on the empty engine."""
import numpy as np
import pytest

from rabit_tpu.learn import histogram


def _np_hist(bins, grad, hess, nbin):
    n, f = bins.shape
    out = np.zeros((f, nbin, 2), np.float64)
    for j in range(f):
        for b in range(nbin):
            m = bins[:, j] == b
            out[j, b, 0] = grad[m].sum()
            out[j, b, 1] = hess[m].sum()
    return out.astype(np.float32)


@pytest.mark.parametrize("n,f,nbin", [(1000, 5, 16), (513, 3, 7)])
def test_build_local_matches_numpy(n, f, nbin):
    rng = np.random.default_rng(0)
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.random(n).astype(np.float32)
    got = np.asarray(histogram.build_local(
        bins, grad, hess, nbin, row_block=256, feat_block=2))
    want = _np_hist(bins, grad, hess, nbin)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_quantize_bounds():
    rng = np.random.default_rng(1)
    vals = rng.standard_normal((500, 4)).astype(np.float32)
    bins, cuts = histogram.quantize(vals, 32)
    assert bins.min() >= 0 and bins.max() < 32
    assert cuts.shape == (4, 31)
    # roughly uniform occupancy from quantile cuts
    counts = np.bincount(bins[:, 0], minlength=32)
    assert counts.min() > 0


def test_build_allreduce_empty_engine(empty_engine):
    rng = np.random.default_rng(2)
    bins = rng.integers(0, 8, (300, 4)).astype(np.int32)
    grad = rng.standard_normal(300).astype(np.float32)
    hess = np.ones(300, np.float32)
    got = histogram.build_allreduce(bins, grad, hess, 8,
                                    row_block=128, feat_block=4)
    want = _np_hist(bins, grad, hess, 8)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)
    # hessian column of counts sums to n
    assert got[:, :, 1].sum() == pytest.approx(4 * 300)


@pytest.mark.parametrize("n,f,nbin", [(1000, 5, 16), (513, 3, 7),
                                      (300, 9, 256)])
def test_pallas_kernel_matches_numpy(n, f, nbin):
    rng = np.random.default_rng(3)
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.random(n).astype(np.float32)
    want = _np_hist(bins, grad, hess, nbin)
    # interpret-mode fused kernel: f32 exact path, bf16 default path
    got = np.asarray(histogram.build_local(
        bins, grad, hess, nbin, use_pallas=True, compute_dtype="float32"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)
    got16 = np.asarray(histogram.build_local(
        bins, grad, hess, nbin, use_pallas=True))
    np.testing.assert_allclose(got16, want, rtol=2e-2, atol=5e-2)


def test_multi_channel_kernel_matches_per_node():
    # per-node level histograms from the (nw, n) weight matrix must
    # equal node-by-node builds
    from rabit_tpu.ops.histogram_kernel import hist_fused_multi

    rng = np.random.default_rng(4)
    n, f, nbin, m = 600, 4, 16, 3
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    node = rng.integers(0, m, n).astype(np.int32)
    w = np.stack([grad * (node == v) for v in range(m)])
    out = np.asarray(hist_fused_multi(bins.T, w, nbin, interpret=True,
                                      compute_dtype="float32"))
    assert out.shape == (m, f, nbin)
    for v in range(m):
        want = _np_hist(bins, w[v], np.ones(n, np.float32), nbin)[:, :, 0]
        np.testing.assert_allclose(out[v], want, rtol=1e-4, atol=1e-3)


def test_build_level_local_pallas_matches_fallback():
    rng = np.random.default_rng(5)
    n, f, nbin, m = 400, 3, 8, 2
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.random(n).astype(np.float32)
    node = rng.integers(0, m, n).astype(np.int32)
    got = np.asarray(histogram.build_level_local(
        bins, grad, hess, node, [0, 1], nbin, use_pallas=True,
        compute_dtype="float32"))
    want = np.asarray(histogram.build_level_local(
        bins, grad, hess, node, [0, 1], nbin, use_pallas=False))
    assert got.shape == (m, f, nbin, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_build_level_chunks_past_channel_budget():
    # 40 nodes -> 80 weight channels > the kernel's 64-channel budget:
    # the level builder must chunk and concatenate
    rng = np.random.default_rng(7)
    n, f, nbin, m = 300, 2, 8, 40
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.random(n).astype(np.float32)
    node = rng.integers(0, m, n).astype(np.int32)
    got = np.asarray(histogram.build_level_local(
        bins, grad, hess, node, list(range(m)), nbin, use_pallas=True,
        compute_dtype="float32"))
    want = np.asarray(histogram.build_level_local(
        bins, grad, hess, node, list(range(m)), nbin, use_pallas=False))
    assert got.shape == (m, f, nbin, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_build_level_allreduce_empty_engine(empty_engine):
    rng = np.random.default_rng(6)
    n, f, nbin = 200, 3, 8
    bins = rng.integers(0, nbin, (n, f)).astype(np.int32)
    grad = rng.standard_normal(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    node = np.zeros(n, np.int32)
    got = histogram.build_level_allreduce(bins, grad, hess, node, [0], nbin)
    want = _np_hist(bins, grad, hess, nbin)
    np.testing.assert_allclose(np.asarray(got[0]), want, rtol=1e-4,
                               atol=1e-3)


def test_split_gain_prefers_clean_split():
    # two clusters: negative gradients in low bins, positive in high bins
    nbin = 8
    hist = np.zeros((1, nbin, 2), np.float32)
    hist[0, :4, 0] = -5.0
    hist[0, 4:, 0] = +5.0
    hist[0, :, 1] = 10.0
    gain = histogram.split_gain(hist)
    assert gain.shape == (1, nbin - 1)
    assert gain.argmax() == 3  # the boundary between the clusters
