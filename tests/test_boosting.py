"""Boosting tests: single-worker learning + distributed equivalence."""
import sys

import numpy as np
import pytest

from rabit_tpu.learn import boosting


def _xor_data(n=600, seed=0):
    """Non-linearly separable data a single linear model cannot fit."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
    return X, y


def test_boosting_learns_xor(empty_engine):
    X, y = _xor_data()
    model = boosting.train(X, y, num_round=20, max_depth=3, nbin=16)
    p = model.predict(X)
    acc = ((p > 0.5) == (y > 0.5)).mean()
    assert acc > 0.95, acc
    assert len(model.trees) == 20


def test_boosting_squared_loss(empty_engine):
    rng = np.random.default_rng(1)
    X = rng.uniform(-1, 1, (500, 3)).astype(np.float32)
    y = (2.0 * X[:, 0] - X[:, 1]).astype(np.float32)
    model = boosting.train(X, y, num_round=30, max_depth=3, nbin=32,
                           loss="squared", learning_rate=0.3)
    pred = model.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05, mse


def test_boosting_resume(empty_engine):
    """Training 10 rounds straight == 5 rounds, 'crash', resume to 10."""
    import rabit_tpu

    X, y = _xor_data()
    ref = boosting.train(X, y, num_round=10, max_depth=2, nbin=16)
    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    boosting.train(X, y, num_round=5, max_depth=2, nbin=16)
    # same process keeps the in-memory checkpoint (world=1 empty engine)
    resumed = boosting.train(X, y, num_round=10, max_depth=2, nbin=16)
    assert len(resumed.trees) == 10
    np.testing.assert_allclose(resumed.predict(X), ref.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_boosting_distributed_with_fault(tmp_path, native_lib):
    """Rank 1 dies mid-training (version 2); the restart resumes from
    the round-2 checkpoint and the job still converges with identical
    models everywhere."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)],
                  extra_env={"RABIT_ENGINE": "mock",
                             "RABIT_MOCK": "1,2,0,0"})
    assert code == 0


def test_boosting_distributed_xla_engine(tmp_path):
    """Boosting over the XLA engine: the per-level histogram allreduce
    rides the device data plane (jax.Array through the engine) while
    cuts/checkpoints use the control plane."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)],
                  extra_env={"RABIT_ENGINE": "xla"})
    assert code == 0


def test_boosting_distributed(tmp_path):
    """2-worker sharded training: identical models on every rank (all
    split decisions ride the allreduced histogram) and the ensemble
    still learns the function."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)])
    assert code == 0


def _missing_xor_data(n=600, seed=0, frac=0.25):
    """XOR data with a fraction of feature-0 entries knocked out to
    NaN: a learner that routes missing rows well keeps most accuracy."""
    X, y = _xor_data(n=n, seed=seed)
    rng = np.random.default_rng(seed + 99)
    X[rng.random(n) < frac, 0] = np.nan
    return X, y


def test_boosting_missing_values(empty_engine):
    """NaN features ride the dedicated missing bin; every split learns
    a default direction (XGBoost's sparsity-aware splits) and predict
    routes NaN rows the same way."""
    X, y = _missing_xor_data()
    model = boosting.train(X, y, num_round=25, max_depth=3, nbin=16)
    # some split actually chose to send missing rows RIGHT — the
    # direction was learned, not hardcoded
    directions = {node.default_left for tree in model.trees
                  for node in tree if node.feature >= 0}
    assert directions == {True, False}, directions
    p = model.predict(X)
    acc = ((p > 0.5) == (y > 0.5)).mean()
    # complete rows must be fit well; NaN rows on feature 0 are
    # inherently ambiguous for XOR, so measure on the complete subset
    complete = ~np.isnan(X[:, 0])
    acc_c = ((p[complete] > 0.5) == (y[complete] > 0.5)).mean()
    assert acc_c > 0.93, (acc, acc_c)


def test_boosting_subsample(empty_engine):
    """Stochastic GBDT: subsample<1 still learns XOR and resuming from
    a mid-run checkpoint replays the exact per-round sample (bit-equal
    final model)."""
    import rabit_tpu

    X, y = _xor_data()
    ref = boosting.train(X, y, num_round=20, max_depth=3, nbin=16,
                         subsample=0.7, seed=5)
    acc = ((ref.predict(X) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.93, acc
    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    boosting.train(X, y, num_round=9, max_depth=3, nbin=16,
                   subsample=0.7, seed=5)
    resumed = boosting.train(X, y, num_round=20, max_depth=3, nbin=16,
                             subsample=0.7, seed=5)
    np.testing.assert_allclose(resumed.predict(X), ref.predict(X),
                               rtol=1e-6)


def test_boosting_distributed_world4_vs_oracle(tmp_path, empty_engine):
    """World-4 sharded training with missing values + row subsampling
    must match a single-process oracle's quality (VERDICT r4 #8): the
    distributed ensemble's accuracy stays within 3 points of a
    full-data single-process model on the same data."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _missing_xor_data(n=800, frac=0.2)
    oracle = boosting.train(X, y, num_round=15, max_depth=3, nbin=16)
    oracle_acc = ((oracle.predict(X) > 0.5) == (y > 0.5)).mean()
    import rabit_tpu

    rabit_tpu.finalize()
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(4, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)],
                  extra_env={"BOOST_SUBSAMPLE": "0.8",
                             "BOOST_MIN_ACC": str(oracle_acc - 0.03)})
    assert code == 0
