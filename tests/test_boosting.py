"""Boosting tests: single-worker learning + distributed equivalence."""
import sys

import numpy as np
import pytest

from rabit_tpu.learn import boosting


def _xor_data(n=600, seed=0):
    """Non-linearly separable data a single linear model cannot fit."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
    return X, y


def test_boosting_learns_xor(empty_engine):
    X, y = _xor_data()
    model = boosting.train(X, y, num_round=20, max_depth=3, nbin=16)
    p = model.predict(X)
    acc = ((p > 0.5) == (y > 0.5)).mean()
    assert acc > 0.95, acc
    assert len(model.trees) == 20


def test_boosting_squared_loss(empty_engine):
    rng = np.random.default_rng(1)
    X = rng.uniform(-1, 1, (500, 3)).astype(np.float32)
    y = (2.0 * X[:, 0] - X[:, 1]).astype(np.float32)
    model = boosting.train(X, y, num_round=30, max_depth=3, nbin=32,
                           loss="squared", learning_rate=0.3)
    pred = model.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05, mse


def test_boosting_resume(empty_engine):
    """Training 10 rounds straight == 5 rounds, 'crash', resume to 10."""
    import rabit_tpu

    X, y = _xor_data()
    ref = boosting.train(X, y, num_round=10, max_depth=2, nbin=16)
    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    boosting.train(X, y, num_round=5, max_depth=2, nbin=16)
    # same process keeps the in-memory checkpoint (world=1 empty engine)
    resumed = boosting.train(X, y, num_round=10, max_depth=2, nbin=16)
    assert len(resumed.trees) == 10
    np.testing.assert_allclose(resumed.predict(X), ref.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_boosting_distributed_with_fault(tmp_path, native_lib):
    """Rank 1 dies mid-training (version 2); the restart resumes from
    the round-2 checkpoint and the job still converges with identical
    models everywhere."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)],
                  extra_env={"RABIT_ENGINE": "mock",
                             "RABIT_MOCK": "1,2,0,0"})
    assert code == 0


def test_boosting_distributed_xla_engine(tmp_path):
    """Boosting over the XLA engine: the per-level histogram allreduce
    rides the device data plane (jax.Array through the engine) while
    cuts/checkpoints use the control plane."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)],
                  extra_env={"RABIT_ENGINE": "xla"})
    assert code == 0


def test_boosting_distributed(tmp_path):
    """2-worker sharded training: identical models on every rank (all
    split decisions ride the allreduced histogram) and the ensemble
    still learns the function."""
    from rabit_tpu.tracker.launch_local import launch

    X, y = _xor_data(n=400)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    code = launch(2, [sys.executable, "tests/workers/boosting_dist.py",
                      str(tmp_path)])
    assert code == 0
