"""Fault-tolerance scenario matrix.

TPU-native equivalent of the reference's recovery test matrix
(reference: test/test.mk:7-24 — model/local/lazy recover with single,
same-point, and repeated deaths).  Kill-points are
(rank,version,seqno,ndeath) tuples; the keepalive launcher restarts dead
workers with an incremented trial counter.

The matrix runs against BOTH robust engines: ``mock`` (the native C++
engine with fault injection, skipped when the library doesn't build)
and ``pyrobust`` (the pure-Python rebuild of the same protocol,
rabit_tpu/engine/robust.py — no native library needed, same RABIT_MOCK
kill-point format).  Native-only observability tests (routed-traffic
accounting, buffer-pool recycling) stay on the ``native_lib`` fixture.

Seqno map per iteration (seq resets at each checkpoint):
  model_recover: 0 = MAX allreduce, 1 = broadcast, 2 = SUM allreduce
  local_recover: 0 = MAX allreduce (lazy prepare), 1 = SUM allreduce
  lazy_recover:  0 = SUM allreduce
  (1<<20) = at CheckPoint, (1<<20)+1 = at LoadCheckPoint
"""
import sys

import pytest

pytestmark = pytest.mark.recovery

CKPT = 1 << 20
LOAD = CKPT + 1


@pytest.fixture(params=["mock", "pyrobust"])
def engine(request):
    """Robust engine under test; the native mock needs the built .so."""
    if request.param == "mock":
        request.getfixturevalue("native_lib")
    return request.param


def _run(worker, world, mock, ndata=1000, niter=3, engine="mock",
         extra=None):
    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_ENGINE": engine}
    if mock:
        env["RABIT_MOCK"] = ";".join(",".join(map(str, m)) for m in mock)
    env.update(extra or {})
    return launch(world, [sys.executable, f"tests/workers/{worker}.py",
                          str(ndata), str(niter)], extra_env=env)


# ---------------------------------------------------------------- no faults
@pytest.mark.parametrize("worker",
                         ["model_recover", "local_recover", "lazy_recover"])
def test_no_faults(worker, engine):
    assert _run(worker, 4, mock=[], engine=engine) == 0


# ------------------------------------------------------------ single deaths
def test_model_recover_single_death(engine):
    # rank 0 dies at version 0 seq 1 (mid-iteration, before broadcast)
    assert _run("model_recover", 4, [(0, 0, 1, 0)], engine=engine) == 0


def test_model_recover_two_deaths_different_versions(engine):
    # the reference's flagship case: rank 0 dies at v0, rank 1 at v1
    # (reference: test/test.mk model_recover_10_10k)
    assert _run("model_recover", 4, [(0, 0, 1, 0), (1, 1, 1, 0)],
                engine=engine) == 0


def test_death_at_checkpoint(engine):
    assert _run("model_recover", 4, [(2, 1, CKPT, 0)], engine=engine) == 0


def test_death_at_load(engine):
    # rank 3 dies at its very first LoadCheckPoint call
    assert _run("model_recover", 4, [(3, 0, LOAD, 0)], engine=engine) == 0


# ---------------------------------------------------------------- die same
def test_model_recover_die_same(engine):
    # several ranks die at the same collective
    # (reference: test/test.mk model_recover_10_10k_die_same)
    assert _run("model_recover", 5,
                [(0, 1, 0, 0), (1, 1, 0, 0), (3, 1, 0, 0)],
                engine=engine) == 0


# ---------------------------------------------------------------- die hard
def test_model_recover_die_hard(engine):
    # rank 1 dies, restarts, and dies again during recovery; rank 0 also
    # dies at the same point (reference: test/test.mk ..._die_hard with
    # mock=1,1,1,1 killing a node on its second life)
    assert _run("model_recover", 4,
                [(1, 1, 1, 0), (0, 1, 1, 0), (1, 1, 1, 1)],
                engine=engine) == 0


def test_repeated_deaths_across_versions(engine):
    assert _run("model_recover", 4,
                [(2, 0, 0, 0), (2, 1, 1, 0), (2, 2, 2, 0)], niter=4,
                engine=engine) == 0


# ------------------------------------------------------------ local / lazy
def test_local_recover_death(engine):
    # the dying rank's local model must come back from ring replicas
    assert _run("local_recover", 4, [(1, 1, 0, 0)], engine=engine) == 0


def test_local_recover_adjacent_deaths(engine):
    # two adjacent ranks die at once: both local models must survive
    # (num_local_replica defaults to 2)
    assert _run("local_recover", 5, [(1, 1, 0, 0), (2, 1, 0, 0)],
                engine=engine) == 0


def test_lazy_recover_death(engine):
    assert _run("lazy_recover", 4, [(2, 1, 0, 0)], engine=engine) == 0


def test_lazy_recover_die_same(engine):
    assert _run("lazy_recover", 5, [(0, 1, 0, 0), (2, 1, 0, 0)],
                engine=engine) == 0


# ------------------------------------------- chunked collectives + faults
def test_recover_with_chunked_collectives(engine):
    """Deaths while payloads are 32x the rabit_reduce_buffer budget: the
    chunked tree/ring paths must fail cleanly mid-stream and replay
    correctly (reference analogue: reduce_buffer chunking under the
    recovery protocol, src/allreduce_base.cc:326-491 +
    src/allreduce_robust.cc:73-105)."""
    assert _run("model_recover", 4, [(0, 0, 1, 0), (1, 1, 1, 0)],
                ndata=500000, engine=engine,
                extra={"RABIT_REDUCE_BUFFER": "64KB"}) == 0


# -------------------------------------------------- hung-worker watchdog
def test_hung_worker_recovers_fast(engine, tmp_path):
    """A SIGSTOP'd (hung-but-alive) worker must be detected and replaced
    in seconds: peers hit the tunable link timeout -> recover rendezvous;
    the tracker watchdog flags the silent rank; the launcher kills and
    restarts it; the job completes well under 30 s (the old fixed 600 s
    waits wedged the round for ~10 minutes).  Reference analogue: errno
    classification / exception-set handling, src/allreduce_base.cc:392-397
    — plus the hung-peer case the reference leaves to its job manager."""
    import time

    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_ENGINE": engine, "RABIT_TIMEOUT_SEC": "6",
           "RABIT_STALL_DIR": str(tmp_path)}
    t0 = time.monotonic()
    code = launch(4, [sys.executable, "tests/workers/stall_worker.py",
                      "1000", "3"], extra_env=env, watchdog_sec=4)
    elapsed = time.monotonic() - t0
    assert code == 0
    assert elapsed < 30, f"hung-worker recovery took {elapsed:.1f}s"
    assert (tmp_path / "stalled").exists()  # the stall actually happened


def test_last_op_replayed_contract(engine):
    """`last_op_replayed` is True exactly for cache-served catch-up ops
    of a relaunched rank (False for fresh ops and for the op it rejoins
    mid-flight) — the contract the XLA engine's replay-aware device-
    plane re-formation depends on."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(3, [sys.executable, "tests/workers/replay_flag.py"],
                  extra_env={"RABIT_ENGINE": engine,
                             "RABIT_MOCK": "1,0,1,0"})
    assert code == 0


# ------------------------------------------------------ recovery telemetry
def test_recovery_event_trace(tmp_path):
    """The single-death kill-point case, extended with telemetry: every
    survivor's event trace must record the documented recovery phase
    sequence — link_error -> rendezvous -> replay -> resume — as a
    subsequence (doc/observability.md; pyrobust-only, the native engine
    keeps its recovery internals opaque to the binding layer)."""
    import json

    assert _run("model_recover", 4, [(0, 0, 1, 0)], engine="pyrobust",
                extra={"RABIT_OBS_DIR": str(tmp_path)}) == 0
    for r in (1, 2, 3):  # the survivors (rank 0 is the injected death)
        f = tmp_path / f"events.rank{r}.jsonl"
        assert f.exists(), f"survivor rank {r} never dumped its trace"
        events = [json.loads(ln) for ln in f.read_text().splitlines()]
        phases = [e["phase"] for e in events if e["name"] == "recovery"]
        it = iter(phases)
        assert all(p in it for p in
                   ["link_error", "rendezvous", "replay", "resume"]), \
            (r, phases)
        # op spans carry the robust protocol coordinates
        ops = [e for e in events if e["name"] == "op"]
        assert ops and all("seqno" in e and "version" in e and
                           "dur" in e and "nbytes" in e for e in ops)


# ------------------------------------------------------- replay semantics
def test_replay_prepare_skip_and_cache_clear(engine):
    """A survivor-cached collective replayed to a relaunched rank must
    skip its `prepare_fun` (the lazy-preparation contract,
    engine/interface.py) and report `last_op_replayed`; the result cache
    must be dropped at every checkpoint() commit (seqnos restart per
    version span)."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(3, [sys.executable, "tests/workers/replay_cache.py"],
                  extra_env={"RABIT_ENGINE": engine,
                             "RABIT_MOCK": "1,0,1,0"})
    assert code == 0


# ------------------------------------------------------- routed recovery
def test_routed_recovery_traffic(native_lib, tmp_path):
    """Recovery payload must flow only along holder->requester tree
    paths: with ONE dead rank in a world of 10, the summed served bytes
    stay O(tree-depth x replayed-payload) — well below the
    O(world x payload) a broadcast-to-all serving scheme costs
    (reference analogue: requester routing, allreduce_robust.cc:526-700
    + MsgPassing allreduce_robust-inl.h:33-158).  Native-only: the
    pyrobust engine deliberately keeps the simple broadcast-to-all
    serving round (see rabit_tpu/engine/robust.py)."""
    from rabit_tpu.tracker.launch_local import launch

    ndata = 65536          # MAX allreduce result = 256 KB (f32)
    world = 10
    env = {"RABIT_ENGINE": "mock",
           "RABIT_MOCK": "5,1,1,0",   # rank 5 dies at v1 seq1: replays seq0
           "RABIT_TRAFFIC_DIR": str(tmp_path)}
    code = launch(world, [sys.executable, "tests/workers/model_recover.py",
                          str(ndata), "3"], extra_env=env)
    assert code == 0
    files = sorted(tmp_path.glob("routed.*"))
    assert len(files) == world, files
    total = sum(int(f.read_text()) for f in files)
    replayed = ndata * 4               # the seq-0 MAX result
    assert total > 0, "recovery happened but nothing was served"
    # broadcast-to-all moves >= (world-1) x replayed bytes; the routed
    # path is bounded by the holder->requester path length (~tree depth)
    assert total < (world - 1) * replayed // 2, (total, replayed)


# ----------------------------------------------------- bigger world, stripes
def test_model_recover_world10_striped(engine):
    # world 10 -> stripe round = 2: replay must find results on the
    # striped holders, not just the latest (reference: striping
    # src/allreduce_robust.cc:86-89; pyrobust mirrors it)
    assert _run("model_recover", 10, [(0, 1, 1, 0), (5, 2, 2, 0)],
                ndata=10000, engine=engine) == 0


# ------------------------------------------------ buffer-pool observability
def test_striped_buffer_pool_recycles(native_lib, capfd):
    """The retired-buffer pool must actually fire (round-5 perf work):
    under striped pruning every op retires a cache buffer and the next
    op must swap it back in instead of fresh-allocating.  Pinned via the
    mock engine's report_stats line because the recycle path once
    regressed invisibly — a capacity()==0 gate never matched moved-from
    strings' 15-byte SSO capacity, and no behavior test noticed.
    Native-only: pyrobust has no buffer pool by design."""
    import re

    from rabit_tpu.tracker.launch_local import launch

    code = launch(4, [sys.executable, "tests/workers/model_recover.py",
                      "100000", "4"],
                  extra_env={"RABIT_ENGINE": "mock",
                             "RABIT_GLOBAL_REPLICA": "1",
                             "RABIT_REPORT_STATS": "1"})
    assert code == 0
    out = capfd.readouterr()
    hits = [int(m.group(1)) for m in
            re.finditer(r"pool_hits_total=(\d+)", out.out + out.err)]
    assert hits, "report_stats line with pool_hits_total never seen"
    # 4 iterations x (2 ring-size allreduces + 1 broadcast) per rank:
    # the recycle must fire many times on every rank by the last report
    assert max(hits) >= 4, hits
