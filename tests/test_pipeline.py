"""Chunk-pipelined collective hops (doc/performance.md "Hop
pipelining").

The contracts pinned here:

* the :class:`~rabit_tpu.transport.pump.HopPipeline` primitive — push/
  pop ordering, the depth window, recv-only hops, idle-timeout typed
  LinkError, and the framed-link completion rule (a popped chunk's send
  region is safe to mutate: frames reference payload, so completion
  waits for the tx backlog);
* **depth bit-parity**: for every pipelined schedule (ring / halving /
  swing / hier's leader ring) and wire codec, the collective results
  are bit-identical across ``rabit_pipeline_depth`` 1/2/4 — depth 1 IS
  the legacy serial hop loop, so this is also the legacy-identity pin —
  with the exactness matrix (``sched_parity``) re-run at depth 4;
* composition: pyrobust kill-point replay is bit-identical with the
  pipeline + int8 armed, a chaos mid-stream reset recovers on a
  pipelined schedule, and ``rabit_reduce_buffer`` remains an honest
  per-op scratch ceiling with the depth window's extra in-flight chunk
  leases counted;
* the directive's per-op codec override (``bytes:sched/codec``): wire
  format round-trips both directions with the old plain form pinned,
  the engine arms the named codec for the dominant bucket only, and a
  ``codec=False`` opt-out still beats it.
"""
import os
import socket
import sys
import threading

import numpy as np
import pytest

pytestmark = pytest.mark.pipeline

PIPE_SCHEDS = ["ring", "halving", "swing", "hier"]
DEPTHS = [1, 2, 4]


def _groups(world: int) -> str:
    return ",".join(str(i // ((world + 1) // 2)) for i in range(world))


def _launch(worker, world, extra_env=None, args=(), tracker_groups=None):
    from rabit_tpu.tracker.launch_local import launch

    saved = os.environ.get("RABIT_TRACKER_GROUPS")
    try:
        if tracker_groups is not None:
            os.environ["RABIT_TRACKER_GROUPS"] = tracker_groups
        else:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        return launch(world, [sys.executable,
                              f"tests/workers/{worker}.py",
                              *map(str, args)], extra_env=extra_env or {})
    finally:
        if saved is None:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        else:
            os.environ["RABIT_TRACKER_GROUPS"] = saved


# ------------------------------------------------- HopPipeline units
def _link_pair(frames=False, timeout=5.0):
    from rabit_tpu.transport.tcp import TcpLink

    a, b = socket.socketpair()
    return (TcpLink(a, 1, timeout, frames=frames),
            TcpLink(b, 0, timeout, frames=frames))


def test_hop_pipeline_push_pop_order_and_window():
    """Chunks complete strictly in push order; the echoed payload lands
    in the right per-chunk buffer; inflight tracks the window."""
    from rabit_tpu.transport.pump import HopPipeline

    la, lb = _link_pair()
    nchunks, csz = 8, 4096
    sends = [bytes([i]) * csz for i in range(nchunks)]

    def peer():
        buf = memoryview(bytearray(csz))
        for _ in range(nchunks):
            lb.recv_exact(csz, buf)
            lb.sendall(bytes(x ^ 0xFF for x in buf[:4]) + bytes(buf[4:]))

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    recvs = [memoryview(bytearray(csz)) for _ in range(nchunks)]
    pipe = HopPipeline(la, la, 5.0)
    try:
        depth, popped = 2, []
        for i in range(nchunks):
            if pipe.inflight >= depth:
                popped.append(pipe.pop())
            pipe.push([memoryview(sends[i])], [recvs[i]], i)
            assert pipe.inflight <= depth
        while pipe.inflight:
            popped.append(pipe.pop())
        pipe.close()
    except BaseException:
        pipe.abort()
        raise
    t.join(timeout=5)
    assert popped == list(range(nchunks))
    for i, rv in enumerate(recvs):
        assert bytes(rv[:4]) == bytes([i ^ 0xFF]) * 4
        assert bytes(rv[4:]) == bytes([i]) * (csz - 4)
    la.close()
    lb.close()


def test_hop_pipeline_recv_only_and_empty_sides():
    """The halving-fold shape: pushes with no send side (and a fully
    empty chunk) complete on recv alone."""
    from rabit_tpu.transport.pump import HopPipeline

    la, lb = _link_pair()
    payload = bytes(range(256)) * 16

    def peer():
        lb.sendall(payload)

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    half = len(payload) // 2
    r1 = memoryview(bytearray(half))
    r2 = memoryview(bytearray(half))
    pipe = HopPipeline(la, la, 5.0)
    try:
        pipe.push([], [r1], "a")
        pipe.push([], [], "empty")
        pipe.push([], [r2], "b")
        assert pipe.pop() == "a"
        assert pipe.pop() == "empty"
        assert pipe.pop() == "b"
        pipe.close()
    except BaseException:
        pipe.abort()
        raise
    t.join(timeout=5)
    assert bytes(r1) + bytes(r2) == payload
    la.close()
    lb.close()


def test_hop_pipeline_idle_timeout_is_typed():
    from rabit_tpu.transport.base import LinkError
    from rabit_tpu.transport.pump import HopPipeline

    la, lb = _link_pair(timeout=0.2)
    pipe = HopPipeline(la, la, 0.2)
    try:
        pipe.push([], [memoryview(bytearray(64))], 0)
        with pytest.raises(LinkError):
            pipe.pop()
    finally:
        pipe.abort()
        la.close()
        lb.close()


def test_hop_pipeline_framed_pop_means_safe_to_mutate():
    """Integrity frames reference the caller's payload (no copy): a
    popped chunk's send region must already be ON the wire, or a
    mutating caller (swing merges in place) would corrupt frames still
    pointing at it.  Mutate right after pop; the peer must see the
    pre-mutation bytes."""
    from rabit_tpu.transport.pump import HopPipeline

    la, lb = _link_pair(frames=True)
    csz = 2048
    got = []

    def peer():
        buf = memoryview(bytearray(csz))
        for _ in range(2):
            lb.recv_exact(csz, buf)
            got.append(bytes(buf))

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    buf = bytearray(b"\x01" * csz)
    pipe = HopPipeline(la, la, 5.0)
    try:
        pipe.push([memoryview(buf)], [], 0)
        assert pipe.pop() == 0
        buf[:] = b"\x02" * csz  # popped => frames drained => safe
        pipe.push([memoryview(buf)], [], 1)
        assert pipe.pop() == 1
        pipe.close()
    except BaseException:
        pipe.abort()
        raise
    t.join(timeout=5)
    assert got == [b"\x01" * csz, b"\x02" * csz]
    la.close()
    lb.close()


# --------------------------------------------- depth bit-parity matrix
def _parity_env(sched: str, depth: int, codec: str) -> dict:
    env = {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": sched,
           "RABIT_REDUCE_BUFFER": "64KB",
           "RABIT_PIPELINE_CHUNK": "16KB",
           "RABIT_PIPELINE_DEPTH": str(depth)}
    if codec != "none":
        env["RABIT_WIRE_CODEC"] = codec
    if depth > 1:
        # The pipelined path must actually run, or the compare is
        # vacuous (pipe.ops asserted worker-side via obs counters).
        env["RABIT_OBS"] = "1"
        env["RABIT_EXPECT_PIPE"] = "1"
    return env


def _depth_digests(tmp_path, sched: str, codec: str, world: int,
                   depths=DEPTHS) -> dict:
    out = {}
    for depth in depths:
        tag = tmp_path / f"{sched}.{codec}.d{depth}"
        assert _launch("pipeline_parity", world,
                       _parity_env(sched, depth, codec), args=(tag,),
                       tracker_groups=_groups(world)) == 0
        out[depth] = [(tmp_path / f"{tag.name}.r{r}").read_text()
                      for r in range(world)]
    return out


# Tier-1 budget (ISSUE 15 satellite): each cell is 3 subprocess
# launches (depth 1/2/4), 10-25 s apiece.  The fast tier keeps one
# representative per axis — ring on the classic wire (the shared ring
# walk the other schedules' pipelined hops also ride), and the one
# codec cell that covers a quantized wire AND the replicated-exchange
# `record` rule (swing-int8).  The rest joins the slow worlds matrix
# below.
@pytest.mark.parametrize("sched", [
    pytest.param(s, marks=() if s == "ring" else (pytest.mark.slow,))
    for s in PIPE_SCHEDS])
def test_depth_parity_classic_world4(sched, tmp_path):
    """Depth {1,2,4} bit-parity on the flagship world, classic wire.
    Depth 1 is the legacy serial hop loop, so this is simultaneously
    the legacy-identity pin for the pipelined paths."""
    digests = _depth_digests(tmp_path, sched, "none", 4)
    assert digests[1] == digests[2] == digests[4], digests


@pytest.mark.parametrize("sched,codec", [
    pytest.param("swing", "int8", id="swing-int8"),
    pytest.param("ring", "int8", id="ring-int8",
                 marks=pytest.mark.slow),
    pytest.param("swing", "bf16", id="swing-bf16",
                 marks=pytest.mark.slow),
    pytest.param("ring", "bf16", id="ring-bf16",
                 marks=pytest.mark.slow)])
def test_depth_parity_codec_world4(sched, codec, tmp_path):
    """Quantized hops through the pipeline: the fused single-pass
    merge + residual ledger must leave identical bits at every depth
    (swing also exercises the one-sided ``record`` rule)."""
    digests = _depth_digests(tmp_path, sched, codec, 4)
    assert digests[1] == digests[2] == digests[4], digests


@pytest.mark.slow
@pytest.mark.parametrize("world", [2, 5])
@pytest.mark.parametrize("codec", ["none", "bf16", "int8"])
@pytest.mark.parametrize("sched", PIPE_SCHEDS)
def test_depth_parity_matrix_worlds(sched, codec, world, tmp_path):
    """The rest of the {2,4,5} worlds matrix (world 4 runs fast above):
    odd worlds hit ragged block partitions + fold pre/post steps,
    world 2 the degenerate single-step rings."""
    digests = _depth_digests(tmp_path, sched, codec, world,
                             depths=[1, 4])
    assert digests[1] == digests[4], digests


def test_depth4_exactness_ladder():
    """The sched_parity exact-arithmetic ladder (zero/1/odd/>chunk
    payloads, tiny reduce buffer) stays value-exact with a deep
    pipeline — dropped, misrouted or double-merged chunks are hard
    value errors independent of the digest compare."""
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "ring",
                    "RABIT_REDUCE_BUFFER": "4KB",
                    "RABIT_PIPELINE_CHUNK": "1KB",
                    "RABIT_PIPELINE_DEPTH": "4"}) == 0


def test_scratch_ceiling_holds_with_pipeline():
    """_note_scratch covers the window's in-flight chunk leases:
    rabit_reduce_buffer stays an honest per-op scratch ceiling with
    the pipeline armed (the worker asserts 0 < peak <= budget)."""
    assert _launch("check_reduce_buffer", 4,
                   {"RABIT_ENGINE": "pysocket",
                    "RABIT_REDUCE_BUFFER": "64KB",
                    "RABIT_PIPELINE_DEPTH": "4"}) == 0


# ------------------------------------------------------- composition
@pytest.mark.recovery
def test_kill_point_replay_pipelined_int8():
    """Kill-point replay with the pipeline + int8 armed: the relaunched
    rank's replayed op serves the EXACT cached bytes (the codec_replay
    worker's CRC consensus), with the hop forced onto a pipelined ring
    at a chunk size that genuinely splits it."""
    assert _launch("codec_replay", 3,
                   extra_env={"RABIT_ENGINE": "pyrobust",
                              "RABIT_WIRE_CODEC": "int8",
                              "RABIT_SCHED": "ring",
                              "RABIT_REDUCE_BUFFER": "4KB",
                              "RABIT_PIPELINE_CHUNK": "1KB",
                              "RABIT_PIPELINE_DEPTH": "4",
                              "RABIT_MOCK": "1,0,1,0"}) == 0


@pytest.mark.chaos
def test_chaos_reset_mid_stream_pipelined():
    """A seeded mid-stream link reset with depth-4 pipelined ring hops:
    the abort path restores every pumped link and pyrobust recovers
    bit-exact (test_sched covers the other schedules at the default
    depth, which is already pipelined)."""
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust", "RABIT_SCHED": "ring",
                    "RABIT_PIPELINE_DEPTH": "4",
                    "RABIT_PIPELINE_CHUNK": "16KB",
                    "RABIT_BACKOFF_BASE_MS": "10",
                    "RABIT_CHAOS": "5:reset@io=1.0*1;ranks=1"},
                   args=["60000", "3"],
                   tracker_groups="0,0,1,1") == 0


# ------------------------------------- directive per-op codec override
def test_directive_codec_wire_format_round_trip():
    """Old plain-name directives parse unchanged BOTH directions; the
    slashed ``name/codec`` form splits into (schedule, codec) and
    encodes back verbatim."""
    from rabit_tpu import sched

    # old format: pinned byte-for-byte both directions
    table = {262144: "halving", 4194304: "hier"}
    raw = sched.encode_directive(table)
    assert raw == "262144:halving,4194304:hier"
    assert sched.decode_directive(raw) == table
    assert sched.directive_entry(table, 262144) == ("halving", None)
    assert sched.directive_codec(table, 262144) is None
    # new format: codec rides the entry, round-trips, splits cleanly
    table2 = {4194304: "ring/int8", 262144: "swing"}
    raw2 = sched.encode_directive(table2)
    assert raw2 == "262144:swing,4194304:ring/int8"
    assert sched.decode_directive(raw2) == table2
    assert sched.directive_entry(table2, 4 << 20) == ("ring", "int8")
    assert sched.directive_pick(table2, 4 << 20) == "ring"
    assert sched.directive_codec(table2, 4 << 20) == "int8"
    assert sched.directive_entry(table2, 262144) == ("swing", None)
    # two-octave cap applies to both halves; malformed tails degrade
    assert sched.directive_entry(table2, 1024) == (None, None)
    assert sched.directive_entry({1024: "ring/"}, 1024) == ("ring", None)
    assert sched.directive_entry({1024: "/int8"}, 1024) == (None, "int8")


def test_engine_arms_directive_codec_per_bucket():
    """_op_codec_for: the named codec is built once with the job's
    block/floor config and armed ONLY for the directive's bucket;
    unknown names keep the job codec, loudly, without raising."""
    from rabit_tpu import sched as sched_mod
    from rabit_tpu.engine.pysocket import PySocketEngine

    eng = PySocketEngine()
    eng._world = 4
    eng._sched_live = sched_mod.decode_directive("262144:ring/int8")
    c = eng._op_codec_for(262144)
    assert c is not None and c.name == "int8"
    assert eng._op_codec_for(262144) is c  # cached instance
    assert eng._op_codec_for(64) is None   # out of bucket: job codec
    # the schedule half only answers ops riding the named wire
    assert eng._pick_schedule(68 << 10, None, 262144,
                              pick_codec="int8").name == "ring"
    # a full-width (opt-out/ineligible) op in the bucket skips the
    # directive and rides its own wire format's static pick
    assert eng._pick_schedule(4 << 10, None, 262144,
                              pick_codec="none").name == "tree"
    # the fp8 alias resolves like any factory name (codec/fp8.py)
    eng._sched_live = sched_mod.decode_directive("262144:ring/fp8")
    c8 = eng._op_codec_for(262144)
    assert c8 is not None and c8.name == "fp8e4m3"
    # unknown codec name: keeps the job codec, never raises
    eng._sched_live = sched_mod.decode_directive("262144:ring/int3")
    assert eng._op_codec_for(262144) is None


def test_directive_codec_override_end_to_end():
    """The override live: a job with NO codec armed runs its dominant
    bucket on the directive's int8 wire (counters prove it), opt-outs
    and out-of-bucket ops stay exact."""
    assert _launch("directive_codec_worker", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_OBS": "1"}) == 0


# -------------------------------------------------------- observability
def test_pipe_counters_surface():
    """pipe.ops / pipe.chunks / pipe.chunks_inflight / pipe.overlap
    stream like every other instrument (the parity workers assert
    pipe.ops rank-side; here: the instruments exist in a snapshot)."""
    from rabit_tpu.obs import Metrics

    m = Metrics()
    m.counter("pipe.ops").inc()
    m.counter("pipe.chunks").inc(8)
    m.gauge("pipe.chunks_inflight").set(2)
    m.gauge("pipe.scratch_bytes").set(32768)
    m.histogram("pipe.overlap.seconds").observe(0.01)
    snap = m.snapshot()
    assert snap["counters"]["pipe.ops"] == 1
    assert snap["counters"]["pipe.chunks"] == 8
    assert snap["gauges"]["pipe.chunks_inflight"] == 2
    assert snap["histograms"]["pipe.overlap.seconds"]["count"] == 1
