"""Worker: rank 1 SIGSTOPs itself once, mid-iteration.

Exercises hung-peer detection end to end: peers hit the link IO timeout
(RABIT_TIMEOUT_SEC) -> LinkError -> recover rendezvous; the tracker's
barrier watchdog reports the silent rank; the launcher SIGKILLs and
restarts it; the restarted life loads the checkpoint and the job
finishes.  The reference detects dead peers via errno classification
(src/allreduce_base.cc:392-397) but has no answer to a hung-but-alive
peer short of the job manager; the watchdog is that answer here.

A marker file (RABIT_STALL_DIR) guards the stop so the restarted life
runs through.
"""
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    version, model = rabit_tpu.load_checkpoint()
    start = model["iter"] if model is not None else 0
    marker = os.path.join(os.environ["RABIT_STALL_DIR"], "stalled")

    for it in range(start, niter):
        a = np.arange(ndata, dtype=np.float32) + rank + it
        rabit_tpu.allreduce(a, rabit_tpu.MAX)
        np.testing.assert_allclose(
            a, np.arange(ndata, dtype=np.float32) + world - 1 + it)

        if rank == 1 and it == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            os.kill(os.getpid(), signal.SIGSTOP)  # hang until watchdog acts

        b = np.ones(ndata, dtype=np.float64) * (rank + 1)
        rabit_tpu.allreduce(b, rabit_tpu.SUM)
        np.testing.assert_allclose(b, world * (world + 1) / 2)

        rabit_tpu.checkpoint({"iter": it + 1})

    rabit_tpu.tracker_print(
        f"stall_worker rank {rank}/{world} finished {niter} iters")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
