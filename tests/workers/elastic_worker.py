"""Worker: elastic membership loop, self-verifying at every world size.

Runs ``niter`` committed iterations under a world that may grow or
shrink at checkpoint-commit boundaries (``RABIT_ELASTIC=1``,
doc/fault_tolerance.md "Elastic membership & tracker HA").  Every
iteration:

* re-shards the dataset with ``splitrows.rows_for_rank(ndata, rank,
  world)`` and proves, live, that the shards are an **exact
  partition**: the SUM-allreduce of the per-shard integer row sums must
  equal the full-dataset total bit-exactly at every world size (a
  dropped or doubled row changes the sum);
* folds world-dependent collective results into ``acc`` so every prior
  iteration's world size affects the final bits (the cold_restart.py
  recurrence, elastic edition);
* commits — and when a commit boundary (or a mid-op scale-down
  recovery) lands a rescale, catches :class:`WorldChangedError`,
  reloads the committed checkpoint, re-shards for the new ``(rank,
  world)`` and resumes.  A late joiner runs the same loop: its fresh
  ``load_checkpoint()`` is served the survivors' committed version.

Driver seams (all optional):

* ``RABIT_OUT_DIR`` — final model to ``final.<task>``; every caught
  rescale appends a JSON line (epoch, version, worlds, rank) to
  ``rescale.<task>.jsonl`` so the soak gate learns the boundary
  versions;
* ``RABIT_STOP_ITER`` — finish cleanly right after committing this
  version (the soak gate's segmented reference runs cover exactly one
  rescale span each);
* ``RABIT_ITER_SLEEP`` — seconds of pacing per iteration, so the
  driver can land joins / kills / tracker restarts mid-training;
* ``RABIT_HOLD_FILE`` — while this path exists the worker parks before
  the iteration's collectives, so the driver can pin the next commit
  boundary (e.g. admit BOTH joiners into one rescale epoch);
* ``RABIT_EXPECT_START_VERSION`` — assert the version a fresh life
  loads (reference runs pin their cold-resume point).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.learn.splitrows import rows_for_rank


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    out_dir = os.environ.get("RABIT_OUT_DIR")
    stop_iter = int(os.environ.get("RABIT_STOP_ITER", "0"))
    pause = float(os.environ.get("RABIT_ITER_SLEEP", "0"))
    task = os.environ.get("RABIT_TASK_ID", "?")
    hold = os.environ.get("RABIT_HOLD_FILE")
    expect = os.environ.get("RABIT_EXPECT_START_VERSION")
    stop_at = stop_iter if stop_iter else niter
    total_rows = ndata * (ndata - 1) // 2  # sum(range(ndata)), exact

    rabit_tpu.init()
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    first_load = True
    acc = np.zeros(ndata, dtype=np.float64)
    while True:
        try:
            version, model = rabit_tpu.load_checkpoint()
            rank = rabit_tpu.get_rank()
            world = rabit_tpu.get_world_size()
            if first_load and expect is not None:
                assert version == int(expect), (version, expect)
            first_load = False
            if model is not None:
                assert version == model["iter"], (version, model["iter"])
                acc = model["acc"]
            else:
                assert version == 0, version
                acc = np.zeros(ndata, dtype=np.float64)
            # Deterministic re-shard for the current (rank, world) —
            # every row lands on exactly one rank, proven below.
            rows = np.asarray(rows_for_rank(ndata, rank, world),
                              dtype=np.int64)
            for it in range(version, stop_at):
                if pause:
                    time.sleep(pause)
                while hold and os.path.exists(hold):
                    time.sleep(0.05)
                # Exact-partition proof at the current world: integer
                # sums in f64 are exact, so equality is bitwise.
                s = np.array([float((rows + it).sum())], dtype=np.float64)
                rabit_tpu.allreduce(s, rabit_tpu.SUM)
                want = float(total_rows + ndata * it)
                assert s[0] == want, (s[0], want, rank, world, it)

                a = np.arange(ndata, dtype=np.float32) + rank + it
                rabit_tpu.allreduce(a, rabit_tpu.MAX)
                np.testing.assert_array_equal(
                    a, np.arange(ndata, dtype=np.float32) + world - 1 + it)

                # acc depends on every prior iteration's world (via a)
                # and on the shard partition (via s): resuming from the
                # wrong version, or a broken reshard, changes the bits.
                acc = acc * 1.000001 + a.astype(np.float64) + s[0] + it
                rabit_tpu.checkpoint({"iter": it + 1, "acc": acc})
            break
        except rabit_tpu.WorldChangedError as e:
            # The committed version (and acc's durable copy) survived
            # the rescale; replay caches and rank-affine shards did
            # not.  Record the boundary for the driver, reload, and
            # resume under the new membership.
            if out_dir:
                with open(os.path.join(out_dir,
                                       f"rescale.{task}.jsonl"), "a") as f:
                    f.write(json.dumps({
                        "epoch": e.epoch, "old_world": e.old_world,
                        "new_world": e.new_world,
                        "version": rabit_tpu.version_number(),
                        "task": task}) + "\n")
            continue

    if out_dir:
        with open(os.path.join(out_dir, f"final.{task}"), "wb") as f:
            f.write(acc.tobytes())
    rabit_tpu.tracker_print(
        f"elastic task {task} rank {rabit_tpu.get_rank()}"
        f"/{rabit_tpu.get_world_size()} finished at v"
        f"{rabit_tpu.version_number()} "
        f"(relaunch {os.environ.get('RABIT_RELAUNCH', '0')})")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
