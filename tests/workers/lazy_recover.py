"""Worker: recovery with lazy_checkpoint (deferred serialization).

TPU-native equivalent of the reference's lazy-checkpoint test
(reference: test/lazy_recover.cc:121, LazyCheckPoint semantics
src/allreduce_robust.h:125-127).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    version, model = rabit_tpu.load_checkpoint()
    start = model["iter"] if model is not None else 0

    # count actual serializations: in a fault-free run the lazy payload
    # must never be materialised (the point of LazyCheckPoint)
    from rabit_tpu.utils import serial

    serialize_calls = [0]
    orig_serialize = serial.serialize_model

    def counting(obj):
        serialize_calls[0] += 1
        return orig_serialize(obj)

    for it in range(start, niter):
        a = np.arange(ndata, dtype=np.float32) * (it + 1) + rank
        rabit_tpu.allreduce(a, rabit_tpu.SUM)
        base = np.arange(ndata, dtype=np.float32) * (it + 1)
        np.testing.assert_allclose(
            a, world * base + world * (world - 1) / 2)

        eng = rabit_tpu.engine.get_engine()
        eng.checkpoint(None, None,
                       lazy_global=lambda it=it: counting({"iter": it + 1}))

    if (os.environ.get("RABIT_MOCK", "") == ""
            and type(eng).__name__ == "NativeEngine"):
        assert serialize_calls[0] == 0, (
            "lazy checkpoint serialized %d times in a fault-free run"
            % serialize_calls[0])

    rabit_tpu.tracker_print(
        f"lazy_recover rank {rank}/{world} done "
        f"(trial {os.environ.get('RABIT_NUM_TRIAL', '0')})")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
