"""Worker program: pyrobust recovery with async handles + bucket fusion.

Each iteration issues a stream of async allreduces — a fused bucket of
small ops (one seqno) plus a solo ring-sized op (next seqno) — waits
them, verifies the sums, and checkpoints.  ``RABIT_MOCK`` kill-points
(set by the test) kill ranks mid-stream; the relaunched rank must be
served the FUSED cached results through the replay protocol and land on
bit-correct values, and survivors must recover mid-flight ops.

Seqno map per version span: 0 = fused bucket, 1 = solo allreduce,
(1<<20) = checkpoint.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM

NSMALL = 6
SMALL = 1000
BIG = 300000  # 1.2MB f32: past rabit_bucket_bytes, rides solo (own seqno)


def member(it: int, j: int, rank: int) -> np.ndarray:
    return np.full(SMALL, float(rank + 1) * (it + 1) + j, np.float32)


def big(it: int, rank: int) -> np.ndarray:
    a = np.full(BIG, float(rank + 1) * (it + 2), np.float32)
    a[::13] += rank
    return a


def main() -> None:
    niter = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    version, _model = rabit_tpu.load_checkpoint()
    for it in range(version, niter):
        arrays = [member(it, j, rank) for j in range(NSMALL)]
        solo = big(it, rank)
        handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrays]
        hsolo = rabit_tpu.allreduce_async(solo, SUM)
        for j, h in enumerate(handles):
            out = h.wait()
            expect = np.full(
                SMALL, (it + 1) * world * (world + 1) / 2.0 + world * j,
                np.float32)
            np.testing.assert_array_equal(out, expect, err_msg=f"it={it} j={j}")
        out = hsolo.wait()
        expect = np.full(BIG, (it + 2) * world * (world + 1) / 2.0,
                         np.float32)
        expect[::13] += world * (world - 1) / 2.0
        np.testing.assert_array_equal(out, expect, err_msg=f"it={it} solo")
        rabit_tpu.checkpoint({"it": it})
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
