"""Worker program: the directive's per-op codec override, end to end.

Simulates the adaptive controller's ``bytes:sched/codec`` directive
form (doc/performance.md "Online adaptation") by installing the same
decoded directive on every rank after init — exactly the replicated
state a rendezvous handout would leave — then runs a stream whose
dominant bucket the directive points at ``ring/int8``:

* eligible f32 SUM ops in the bucket must ride the int8 wire (the
  ``codec.ops.int8`` counter moves, and the pick is ``ring``) even
  though the JOB armed no codec (``rabit_wire_codec`` unset);
* the quantized results must match the exact sum within the int8
  envelope, and error feedback must engage across the repeated stream;
* ops OUTSIDE the bucket (a small payload two+ octaves away) and
  ineligible dtypes stay on the exact classic wire, bit-exact;
* a ``codec=False`` per-op opt-out beats the directive (precision
  opt-outs are sacred), staying bit-exact inside the bucket.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import sched as sched_mod
from rabit_tpu.ops import SUM

BUCKET = 256 << 10  # 64Ki f32 elements


def exact_sum(base: np.ndarray, world: int) -> np.ndarray:
    out = np.zeros_like(base, dtype=np.float64)
    for r in range(world):
        out += base.astype(np.float64) * (r + 1)
    return out


def main() -> None:
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    assert eng._codec is None, "worker expects no job codec armed"
    # The directive every rank would have received from the tracker's
    # controller handout: dominant bucket 256KB -> ring on the int8
    # wire.  Installed identically on every rank, so dispatch stays a
    # collective decision (same contract as the real handout).
    eng._sched_live = sched_mod.decode_directive(f"{BUCKET}:ring/int8")

    nelem = BUCKET // 4
    rng = np.random.default_rng(7)
    base = rng.standard_normal(nelem).astype(np.float32)
    expect = exact_sum(base, world)
    scale = float(np.abs(expect).max())
    for _ in range(3):  # repeated stream: error feedback engages
        a = base * np.float32(rank + 1)
        rabit_tpu.allreduce(a, SUM)
        err = float(np.abs(a.astype(np.float64) - expect).max())
        assert err <= 0.08 * scale, f"int8 envelope blown: {err / scale}"

    # codec=False wins over the directive: bit-exact classic wire.
    a = base * np.float32(rank + 1)
    rabit_tpu.allreduce(a, SUM, codec=False)
    exact32 = exact_sum(base, world).astype(np.float64)
    assert float(np.abs(a.astype(np.float64) - exact32).max()) \
        <= 1e-3 * scale  # f32 summation order noise only
    # Out-of-bucket op (>= two octaves below): classic exact wire.
    small = np.full(64, np.float32(rank + 1))
    rabit_tpu.allreduce(small, SUM)
    np.testing.assert_array_equal(
        small, np.full(64, world * (world + 1) / 2.0, np.float32))
    # Ineligible dtype in the bucket: classic exact wire.
    d = np.full(nelem, np.float64(rank + 1))
    rabit_tpu.allreduce(d, SUM)
    np.testing.assert_array_equal(
        d, np.full(nelem, world * (world + 1) / 2.0, np.float64))

    stats = eng.stats()
    counters = stats.get("counters", {})
    assert counters.get("codec.ops.int8", 0) >= 3, counters
    assert counters.get("sched.pick.ring", 0) >= 3, counters
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
