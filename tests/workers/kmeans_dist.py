"""Distributed kmeans worker: self-verifies the allreduced stats against a
full-data oracle every iteration (the reference's self-verification style,
reference: test/model_recover.cc:29-70), then writes final centroids.

argv: <data_pattern(%d)> <full_data> <k> <max_iter> <out_prefix>
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)


import numpy as np

import rabit_tpu
from rabit_tpu.learn import kmeans, load_libsvm
from rabit_tpu.ops import MAX, SUM
from rabit_tpu.utils.checks import check


def main() -> int:
    pattern, full_path, k, max_iter, out = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5])
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()

    data = load_libsvm(pattern, rank=rank)
    full = load_libsvm(full_path)

    version, restored = rabit_tpu.load_checkpoint()
    if version == 0:
        feat_dim = int(rabit_tpu.allreduce(
            np.array([data.feat_dim], np.int64), MAX)[0])
        check(feat_dim == full.feat_dim, "feat_dim mismatch")
        model = kmeans.init_centroids(data, k, feat_dim, seed=0)
    else:
        model = restored
    feat_dim = model.centroids.shape[1]
    idx, val, _labels, valid = data.to_ell(pad_index=feat_dim, row_block=32)
    # every shard, for the self-verification oracle (each worker recomputes
    # what the allreduce should have produced, the reference's
    # self-verification pattern, test/model_recover.cc:29-70)
    world = rabit_tpu.get_world_size()
    shards = [load_libsvm(pattern, rank=r).to_ell(
        pad_index=feat_dim, row_block=32) for r in range(world)]

    for it in range(version, max_iter):
        stats = np.zeros((k, feat_dim + 1), np.float32)

        def lazy(stats=stats, model=model):
            stats[...] = kmeans.compute_stats(model, idx, val, valid, 32)

        stats = rabit_tpu.allreduce(stats, SUM, prepare_fun=lazy)
        # oracle: same per-shard compute, summed locally
        expect = np.zeros((k, feat_dim + 1), np.float32)
        for s_idx, s_val, _sl, s_valid in shards:
            expect += kmeans.compute_stats(model, s_idx, s_val, s_valid, 32)
        np.testing.assert_allclose(stats, expect, rtol=1e-3, atol=1e-3)

        counts = stats[:, -1:]
        check(bool((counts != 0).all()), "zero cluster")
        model.centroids = (stats[:, :-1] / counts).astype(np.float32)
        model.normalize()
        rabit_tpu.checkpoint(model)

    # all ranks must hold identical centroids
    gathered = rabit_tpu.allgather(model.centroids.reshape(-1))
    for r in range(rabit_tpu.get_world_size()):
        np.testing.assert_allclose(
            gathered[r], model.centroids.reshape(-1), rtol=1e-6)
    if rank == 0:
        np.save(out + ".npy", model.centroids)
    rabit_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
