"""Worker: whole-world kill + durable cold-restart, self-verifying.

The headline gate of the durable checkpoint tier: every rank runs
iterations whose model state (``acc``) depends on all previous
iterations, checkpoints each one, and — when ``RABIT_COLD_DIR`` is set
— SIGKILLs itself right after committing ``RABIT_COLD_KILL_ITER``
(once, marker-guarded).  With EVERY rank dead, no in-memory replica
survives; the supervisor relaunches the world and the relaunched lives
must resume at the last durably committed version (asserted — never
version 0) and finish with ``acc`` bit-identical to an uninterrupted
run (each rank writes it to ``RABIT_OUT_DIR/final.<rank>`` for the
driver to compare).

``RABIT_EXPECT_START_VERSION`` (optional) pins the version a fresh life
must load — the corrupt-newest-blob fallback test uses it to prove the
loader fell back to the next-older valid version.
"""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    cold_dir = os.environ.get("RABIT_COLD_DIR")
    kill_iter = int(os.environ.get("RABIT_COLD_KILL_ITER", "0"))
    marker = (os.path.join(cold_dir, f"killed.{rank}") if cold_dir else None)

    version, model = rabit_tpu.load_checkpoint()
    expect = os.environ.get("RABIT_EXPECT_START_VERSION")
    if expect is not None:
        assert version == int(expect), (version, expect)
    if model is not None:
        start, acc = model["iter"], model["acc"]
    else:
        start, acc = 0, np.zeros(ndata, dtype=np.float64)
    assert version == start, (version, start)
    if marker and os.path.exists(marker):
        # Post-kill life of a kill-ALL round: nothing in memory survived,
        # so resuming anywhere requires the durable tier — never v0.
        assert version >= kill_iter > 0, (version, kill_iter)

    # Optional pacing (RABIT_ITER_SLEEP): the multi-tenant soak needs
    # the run to outlast a co-tenant massacre it times against this
    # worker's checkpoint commits.  RABIT_SLOW_RANK/RABIT_SLOW_EXTRA
    # turn ONE rank into a deliberate straggler (extra sleep before its
    # collectives) — the live-telemetry gates assert the tracker's
    # span merge attributes the slowness to exactly that rank.  Sleeps
    # never change the model bits.
    pause = float(os.environ.get("RABIT_ITER_SLEEP", "0"))
    slow_rank = int(os.environ.get("RABIT_SLOW_RANK", "-1"))
    slow_extra = float(os.environ.get("RABIT_SLOW_EXTRA", "0"))
    if rank == slow_rank and slow_extra > 0:
        pause += slow_extra
    for it in range(start, niter):
        if pause:
            time.sleep(pause)
        a = np.arange(ndata, dtype=np.float32) + rank + it
        rabit_tpu.allreduce(a, rabit_tpu.MAX)
        np.testing.assert_allclose(
            a, np.arange(ndata, dtype=np.float32) + world - 1 + it)

        root = it % world
        obj = {"iter": it, "root": root} if rank == root else None
        obj = rabit_tpu.broadcast(obj, root)
        assert obj == {"iter": it, "root": root}, obj

        b = np.ones(ndata, dtype=np.float64) * (rank + 1)
        rabit_tpu.allreduce(b, rabit_tpu.SUM)
        np.testing.assert_allclose(b, world * (world + 1) / 2)

        # acc depends on every prior iteration: resuming from the wrong
        # version (or losing a committed one) changes the final bits.
        acc = acc * 1.000001 + a.astype(np.float64) + b + it
        rabit_tpu.checkpoint({"iter": it + 1, "acc": acc})
        assert rabit_tpu.version_number() == it + 1

        if marker and it + 1 == kill_iter and not os.path.exists(marker):
            open(marker, "w").close()
            os.kill(os.getpid(), signal.SIGKILL)  # the whole world dies here

    out_dir = os.environ.get("RABIT_OUT_DIR")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, f"final.{rank}"), "wb") as f:
            f.write(acc.tobytes())
    rabit_tpu.tracker_print(
        f"cold_restart rank {rank}/{world} finished {niter} iters "
        f"(relaunch {os.environ.get('RABIT_RELAUNCH', '0')})")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
