"""Worker: one rank SIGKILLs itself mid-collective for the postmortem gate.

Every rank runs lockstep allreduces (exactly one collective per
iteration, so op seqnos are comparable across ranks).  The victim
(``RABIT_PM_KILL_RANK``) dies by SIGKILL immediately BEFORE entering
its ``RABIT_PM_KILL_ITER``-th allreduce — an uncatchable death that
leaves NO flight record of its own.  The survivors wedge inside that
same allreduce until the link timeout escalates to a LinkError, whose
fault path persists their always-on flight recorders
(``RABIT_TRACE_DIR``); ``tools/postmortem.py`` must then name the
victim (the blamed peer that never wrote a record) and the in-flight
op (kind=allreduce, seq == kill_iter) from those records alone
(doc/observability.md "Causal tracing & postmortem").
"""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    kill_rank = int(os.environ.get("RABIT_PM_KILL_RANK", "-1"))
    kill_iter = int(os.environ.get("RABIT_PM_KILL_ITER", "-1"))
    # KILL (default) is the uncatchable corpse of the postmortem gate;
    # TERM exercises the engine's SIGTERM flight-persist handler (the
    # victim leaves a reason="sigterm" record, then dies).
    sig = getattr(signal, "SIG" + os.environ.get("RABIT_PM_SIGNAL",
                                                 "KILL"))
    pause = float(os.environ.get("RABIT_ITER_SLEEP", "0"))

    for it in range(niter):
        if pause:
            # Pacing so the streamed obs frames (hop records ride them)
            # flush between ops when the driver scrapes /trace live.
            time.sleep(pause)
        if rank == kill_rank and it == kill_iter:
            os.kill(os.getpid(), sig)  # mid-collective corpse
            time.sleep(30)  # SIGTERM delivery is asynchronous; park
        a = np.arange(ndata, dtype=np.float64) + rank + it
        rabit_tpu.allreduce(a, rabit_tpu.SUM)
        np.testing.assert_allclose(
            a, world * (np.arange(ndata, dtype=np.float64) + it)
            + world * (world - 1) / 2)

    rabit_tpu.tracker_print(
        f"postmortem_victim rank {rank}/{world} finished {niter} iters")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
