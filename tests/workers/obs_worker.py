"""Worker: fixed collective schedule for the telemetry distributed test.

Runs a known op schedule (niter x [SUM allreduce + rotating-root
broadcast] + one checkpoint), then dumps its engine's ``stats()``
snapshot to ``$RABIT_OBS_DIR/stats.rank<r>.json`` so the parent test can
assert every rank reports identical op counts and byte totals on both
the pysocket and pyrobust engines (tests/test_obs.py).
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    for it in range(niter):
        a = np.ones(ndata, dtype=np.float32) * (rank + 1)
        rabit_tpu.allreduce(a, rabit_tpu.SUM)
        np.testing.assert_allclose(a, world * (world + 1) / 2)
        root = it % world
        obj = rabit_tpu.broadcast({"it": it} if rank == root else None, root)
        assert obj == {"it": it}, obj
    rabit_tpu.checkpoint({"done": niter})

    obs_dir = os.environ["RABIT_OBS_DIR"]
    from rabit_tpu import engine as _em

    os.makedirs(obs_dir, exist_ok=True)
    with open(os.path.join(obs_dir, f"stats.rank{rank}.json"), "w") as f:
        json.dump(_em.get_engine().stats(), f)
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
