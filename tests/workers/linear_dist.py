"""Distributed linear/logistic training worker.

Trains on a per-rank shard; rank 0 writes the final model.  The pytest
side verifies the result equals single-process training on the full data
(gradients/losses sum exactly across shards).

argv: <data_pattern(%d)> <objective> <out_model> [name=value ...]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)


import rabit_tpu
from rabit_tpu.learn import LinearObjFunction


def main() -> int:
    pattern, objective, out_model = sys.argv[1], sys.argv[2], sys.argv[3]
    rabit_tpu.init()
    obj = LinearObjFunction()
    obj.load_data(pattern)
    obj.set_param("objective", objective)
    obj.set_param("silent", "1")
    obj.set_param("row_block", "64")
    obj.set_param("model_out", out_model)
    for a in sys.argv[4:]:
        name, val = a.split("=", 1)
        obj.set_param(name, val)
    obj.run()
    rabit_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
