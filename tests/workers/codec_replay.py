"""Worker: pyrobust kill-point replay with a lossy wire codec armed.

Rank 1 dies at version 0 seqno 1 (mock kill-point) and is relaunched.
Its second life must be served seqno 0 — a QUANTIZED int8-wire
allreduce — from a survivor's cache: ``prepare_fun`` skipped,
``last_op_replayed`` True, and the replayed bytes BIT-IDENTICAL to
what every survivor holds (asserted via an exact CRC consensus over
full-width f64 collectives).  The codec composes below the cache —
results are cached as decoded f32 bytes and the error-feedback commit
is transactional — so replay serves identical bits with any codec.
"""
import os
import sys
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import engine as engmod
from rabit_tpu.ops import MAX, MIN, SUM


def main() -> None:
    trial = int(os.environ.get("RABIT_NUM_TRIAL", 0))
    rabit_tpu.init()
    eng = engmod.get_engine()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    want = os.environ.get("RABIT_WIRE_CODEC", "int8")
    assert eng._codec_label == want, (eng._codec_label, want)

    calls = [0]
    a = np.empty(4096, np.float32)  # 16KB: over the block-scale floor

    def prep():
        calls[0] += 1
        # Deterministic per-rank payload: a replayed life re-presents
        # the same logical op, so the fingerprint consensus holds.
        a[:] = np.linspace(-2.0, 2.0, len(a)) * (rank + 1)

    rabit_tpu.allreduce(a, SUM, prepare_fun=prep)  # seq 0 (quantized)
    if trial > 0 and rank == 1:
        # Relaunched life: seq 0 completed before the kill, so it MUST
        # come from a survivor's cache — lazy prep skipped, flag honest.
        assert eng.last_op_replayed, "replayed codec op not flagged"
        assert calls[0] == 0, "prepare_fun ran on a replayed codec op"
    else:
        assert not eng.last_op_replayed
        assert calls[0] == 1, calls

    # Bit-identity consensus: every rank (including the replayed one)
    # must hold the EXACT same decoded bytes.  CRC over exact
    # full-width f64 collectives (never quantized: f64 is ineligible).
    crc = float(zlib.crc32(a.tobytes()))
    lo = rabit_tpu.allreduce(np.array([crc]), MIN)  # seq 1 (kill-point)
    hi = rabit_tpu.allreduce(np.array([crc]), MAX)  # seq 2
    assert lo[0] == hi[0] == crc, (
        f"replayed codec result diverged: crc {crc} vs "
        f"[{lo[0]}, {hi[0]}]")

    rabit_tpu.tracker_print(
        f"codec_replay rank {rank}/{world} trial {trial} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
