"""Worker: pins the `last_op_replayed` contract of the robust engine.

Rank 1 dies at its second collective (mock kill-point at version 0,
seqno 1, reached before contributing).  Its relaunched life is served
seqno 0 from the survivors' cache — `last_op_replayed` must be True for
exactly that op — and REJOINS seqno 1 mid-flight (the survivors could
never complete it without rank 1), which counts as a current-round
fresh op: False, like every op after it.  The XLA engine's device-plane
re-formation keys its join-vs-skip decision on this distinction.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import engine as engmod

NOPS = 4


def main() -> None:
    trial = int(os.environ.get("RABIT_NUM_TRIAL", 0))
    rabit_tpu.init()  # RABIT_ENGINE=mock from the test
    eng = engmod.get_engine()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    version, _ = rabit_tpu.load_checkpoint()
    assert version == 0  # the job never checkpoints: pure replay test

    for op in range(NOPS):
        a = np.full(16, float(op + 1), np.float64)
        rabit_tpu.allreduce(a, rabit_tpu.SUM)
        np.testing.assert_allclose(a, world * (op + 1))
        replayed = eng.last_op_replayed
        if trial > 0 and rank == 1 and op == 0:
            # the op the first life completed is served from the cache;
            # seq 1 (where it died) was still PENDING on the survivors,
            # so the relaunch joins it fresh — mid-op participation is
            # a current-round value, not a replay
            assert replayed, f"op {op} should be replay-served"
        else:
            assert not replayed, f"op {op} wrongly marked replayed"
    rabit_tpu.tracker_print(
        f"replay_flag rank {rank}/{world} trial {trial} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
