"""Worker: distributed GBDT — every rank must end with the identical
model (split decisions are taken on the allreduced histogram), and the
ensemble must fit the XOR function no single stump can.

argv: <data_dir with X.npy / y.npy>
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)


import numpy as np

import rabit_tpu
from rabit_tpu.learn import boosting


def main() -> int:
    data_dir = sys.argv[1]
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    X = np.load(os.path.join(data_dir, "X.npy"))
    y = np.load(os.path.join(data_dir, "y.npy"))
    Xs, ys = X[rank::world], y[rank::world]

    subsample = float(os.environ.get("BOOST_SUBSAMPLE", "1.0"))
    min_acc = float(os.environ.get("BOOST_MIN_ACC", "0.9"))
    model = boosting.train(Xs, ys, num_round=15, max_depth=3, nbin=16,
                           subsample=subsample)

    # identical predictions everywhere (same model on every rank);
    # with missing values this also pins the learned default directions
    pred = model.predict(X).astype(np.float64)
    gathered = rabit_tpu.allgather(pred)
    for r in range(world):
        np.testing.assert_allclose(gathered[r], pred, rtol=1e-6)

    acc = ((pred > 0.5) == (y > 0.5)).mean()
    assert acc > min_acc, acc
    rabit_tpu.tracker_print(
        f"boosting_dist rank {rank}/{world} acc={acc:.3f} OK")
    rabit_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
