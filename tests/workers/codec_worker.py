"""Worker: accuracy + composition gates for one wire codec.

The codec matrix's worker (tests/test_codec.py): arms a codec via
RABIT_WIRE_CODEC, asserts the engine resolved it, and runs — per
schedule — random-payload parity against an in-run ``codec=False``
oracle within the codec's documented accuracy envelope
(doc/performance.md "Quantized wire codecs"), bit-exactness below the
block-scaled size floor and for opted-out ops, an error-feedback
convergence stream (the residual must compensate, never drift), and a
fused/async bucket pass.

The oracle is ``codec=False`` IN the same run — the exact full-width
wire, deterministic across ranks — so the gate measures exactly the
quantization error, not reduction-order noise.

argv[1] (optional) = the codec name the engine must have resolved
(defaults to $RABIT_WIRE_CODEC).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM

#: documented accuracy envelope per codec: max |err| relative to the
#: result's absmax, across every schedule/world in the matrix.  The
#: per-op bound is ~(quantization events)/qmax of the block absmax —
#: one encode plus up to log2(world)+1 hop requantizations — so int8
#: (qmax 127) sits well under 8e-2 and int4 (qmax 7) under 6e-1; bf16
#: carries ~3 significant digits (doc/performance.md).
#: fp8 is itself floating point, so its per-event error is relative to
#: each VALUE (~half ulp: 2^-4 for e4m3's 3 mantissa bits, 2^-3 for
#: e5m2's 2), not the block absmax — near-absmax elements dominate the
#: rel_err metric, giving ~events*2^-4 (resp. 2^-3) envelopes.
TOL = {"bf16": 4e-2, "int8": 8e-2, "int4": 6e-1,
       "fp8e4m3": 4e-1, "fp8e5m2": 6e-1}

#: block-scaled codecs keep payloads under this exact (factory.py
#: DEFAULT_MIN_BYTES); bf16 has no floor (the historical cast applied
#: at every size and must stay byte-identical to it)
MIN_BYTES = 4 << 10

SCHEDS = ("tree", "ring", "halving", "swing", "hier", "synth", "static")
SIZES = (1, 100, 1023, 4096, 16385)
EF_ITERS = 40


def rel_err(got: np.ndarray, want: np.ndarray) -> float:
    scale = max(float(np.abs(want).max(initial=0.0)), 1e-9)
    return float(np.abs(got - want).max(initial=0.0)) / scale


def main() -> None:
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    codec = (sys.argv[1] if len(sys.argv) > 1
             else os.environ["RABIT_WIRE_CODEC"])
    assert eng._codec_label == codec, (eng._codec_label, codec)
    tol = TOL[codec]
    # every block-scaled codec (int + fp8) honors the size floor; bf16
    # has none (the historical cast applied at every size)
    floor = 0 if codec == "bf16" else MIN_BYTES

    rng = np.random.default_rng(7 + rank)
    for sched in SCHEDS:
        eng.set_schedule(sched)
        for size in SIZES:
            a = rng.standard_normal(size).astype(np.float32)
            exact = a.copy()
            rabit_tpu.allreduce(exact, SUM, codec=False)
            # The opt-out is deterministic: a second codec=False op
            # over the same bytes must be bit-identical.
            again = a.copy()
            rabit_tpu.allreduce(again, SUM, codec=False)
            np.testing.assert_array_equal(
                again, exact, err_msg=f"opt-out nondeterministic "
                f"({sched} size={size})")
            q = a.copy()
            rabit_tpu.allreduce(q, SUM)
            if size * 4 < floor:
                # Below the block-scale floor the wire is classic:
                # exact bits, not merely close.
                np.testing.assert_array_equal(
                    q, exact, err_msg=f"size floor broken "
                    f"({sched} size={size})")
            else:
                err = rel_err(q, exact)
                assert err <= tol, (
                    f"{codec} accuracy envelope broken: {sched} "
                    f"size={size} rel_err={err:.4g} > {tol}")

    # ---- error-feedback stream: repeated allreduce of the SAME ----
    # ---- logical tensor (the learn layer's shape) must not drift ----
    eng.set_schedule("static")
    base = rng.standard_normal(8192).astype(np.float32)
    exact = base.copy()
    rabit_tpu.allreduce(exact, SUM, codec=False)
    errs = []
    for _ in range(EF_ITERS):
        a = base.copy()
        rabit_tpu.allreduce(a, SUM)
        errs.append(rel_err(a, exact))
    head = max(errs[:EF_ITERS // 2])
    tail = max(errs[EF_ITERS // 2:])
    assert tail <= tol, f"EF stream left the envelope: {tail:.4g}"
    # No drift: a residual that accumulated instead of compensating
    # would grow the tail error well past the head of the stream.
    assert tail <= 2.0 * head + 1e-6, (
        f"error-feedback drift: head {head:.4g} -> tail {tail:.4g}")
    if codec in ("int8", "int4"):
        # Dual-sided EF property: the error is zero-mean over the
        # stream, so the time-average of the decoded results converges
        # well inside the single-op envelope.
        acc = np.zeros_like(exact, np.float64)
        for _ in range(EF_ITERS):
            a = base.copy()
            rabit_tpu.allreduce(a, SUM)
            acc += a
        avg_err = rel_err((acc / EF_ITERS).astype(np.float32), exact)
        assert avg_err <= max(errs) / 2 + 1e-6, (
            f"EF bias: stream-average error {avg_err:.4g} not below "
            f"single-op error {max(errs):.4g}")

    # ---- fused/async bucket stream parity ----
    arrs = [rng.standard_normal(2048).astype(np.float32)
            for _ in range(12)]
    exacts = [a.copy() for a in arrs]
    for e in exacts:
        rabit_tpu.allreduce(e, SUM, codec=False)
    handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrs]
    for h in handles:
        h.wait()
    for i, (a, e) in enumerate(zip(arrs, exacts)):
        err = rel_err(a, e)
        assert err <= tol, f"fused stream op {i}: rel_err={err:.4g}"
    # Opted-out members must never share a fused wire op with
    # codec-eligible ones: an interleaved stream stays correct.
    mixed = [rng.standard_normal(2048).astype(np.float32)
             for _ in range(8)]
    mexact = [a.copy() for a in mixed]
    for e in mexact:
        rabit_tpu.allreduce(e, SUM, codec=False)
    handles = [rabit_tpu.allreduce_async(a, SUM, codec=bool(i % 2))
               for i, a in enumerate(mixed)]
    for h in handles:
        h.wait()
    for i, (a, e) in enumerate(zip(mixed, mexact)):
        if i % 2 == 0:
            np.testing.assert_array_equal(
                a, e, err_msg=f"opted-out fused member {i} not exact")
        else:
            assert rel_err(a, e) <= tol, f"mixed stream op {i}"

    rabit_tpu.tracker_print(
        f"codec_worker rank {rank}/{world} codec={codec} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
