"""Worker program: the MPI engine with numeric self-verification.

Runs with a real mpi4py under mpirun, or with the test-only stub runtime
(tests/mpistub) injected via PYTHONPATH — either way the engine body
(rabit_tpu/engine/mpi.py) executes for real
(reference analogue: src/engine_mpi.cc:126-137).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    rabit_tpu.init(rabit_engine="mpi")
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    assert world > 1, "check_mpi expects a multi-rank run"
    assert rabit_tpu.is_distributed()

    # allreduce SUM (IN_PLACE)
    a = np.arange(16, dtype=np.float64) + rank
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    np.testing.assert_allclose(
        a, world * np.arange(16, dtype=np.float64) + world * (world - 1) / 2)

    # allreduce MAX, int dtype
    b = np.full(5, rank, dtype=np.int64)
    rabit_tpu.allreduce(b, rabit_tpu.MAX)
    assert (b == world - 1).all(), b

    # allreduce PROD
    c = np.full(3, 2.0 + rank)
    rabit_tpu.allreduce(c, rabit_tpu.PROD)
    np.testing.assert_allclose(c, np.prod([2.0 + r for r in range(world)]))

    # object broadcast from every root
    for root in range(world):
        obj = {"root": root} if rank == root else None
        assert rabit_tpu.broadcast(obj, root) == {"root": root}

    # allgather
    g = rabit_tpu.allgather(np.array([rank, rank * 3], dtype=np.int32))
    for r in range(world):
        assert (g[r] == [r, 3 * r]).all(), g

    # custom reducer (interface default: allgather + fold)
    d = np.full(4, float(rank + 1))
    rabit_tpu.allreduce_custom(d, lambda dst, src: np.multiply(dst, src,
                                                               out=dst))
    np.testing.assert_allclose(d, np.prod([1.0 + r for r in range(world)]))

    # checkpoint trio (process-local, non-fault-tolerant — reference:
    # src/engine_mpi.cc:56-72)
    version, model = rabit_tpu.load_checkpoint()
    assert version == 0 and model is None
    rabit_tpu.checkpoint({"iter": 1})
    version, model = rabit_tpu.load_checkpoint()
    assert version == 1 and model == {"iter": 1}

    rabit_tpu.tracker_print(f"check_mpi rank {rank}/{world} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
