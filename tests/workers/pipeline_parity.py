"""Worker program: depth bit-parity for the hop pipeline.

Runs a deterministic collective stream — f32 SUM payloads at ragged /
edge / multi-chunk sizes, repeated so an armed codec's error-feedback
stream advances, plus an exact int64 SUM guard — and writes a per-rank
SHA-256 digest of every result's bytes to ``argv[1].r<rank>``.

The pipeline's contract (doc/performance.md "Hop pipelining") is that
results are BIT-identical across ``rabit_pipeline_depth`` values: the
test harness runs this worker once per depth with identical seeds/env
and compares the digest files — any value drift, reordering, torn merge
or residual-ledger divergence between the serial and pipelined hop
loops is a hard digest mismatch.

Env knobs the harness uses: ``RABIT_PIPELINE_DEPTH`` (the depth under
test), ``RABIT_PIPELINE_CHUNK`` / ``RABIT_REDUCE_BUFFER`` (forced small
so every schedule's hops genuinely split into several in-flight
chunks), ``RABIT_SCHED`` (the forced schedule), ``RABIT_WIRE_CODEC``,
and ``RABIT_EXPECT_PIPE=1`` to assert the pipelined path actually ran
(via the ``pipe.ops`` counter — a parity run that silently rode the
serial loop would be vacuous).
"""
import hashlib
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM

# 120_001 f32 = ~480KB: > several 16KB pipeline chunks per hop block at
# every tested world; 4097 exercises the ragged-block edge paths.
SIZES = (0, 1, 7, 4097, 120_001)
REPS = 3


def main() -> None:
    out = sys.argv[1]
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    if os.environ.get("RABIT_CODEC_IMPL_MIXED") == "1" and rank % 2 == 0:
        # Mixed-impl parity (tests/test_native_codec.py): even ranks
        # rebind the compiled kernel post-init while odd ranks stay on
        # numpy — legal precisely because the impl is NOT a collective
        # decision (bit-identical by contract), which is what the
        # harness's digest compare proves end to end.
        from rabit_tpu import codec as codec_mod
        from rabit_tpu import engine as engine_mod

        k = codec_mod.load()
        assert k is not None, codec_mod.load_error()
        eng = engine_mod.get_engine()
        assert eng._codec is not None, "mixed-impl run needs a codec"
        eng._codec._bind_kernel(k)
    digest = hashlib.sha256()

    # One rng, advanced identically on every rank (the base vector is
    # replicated; each rank scales it) — so the stream is deterministic
    # per (size, rep) and identical across depth runs.
    rng = np.random.default_rng(1234)
    for size in SIZES:
        for rep in range(REPS):
            base = rng.standard_normal(size).astype(np.float32)
            a = (base * np.float32(rank + 1 + rep)).copy()
            rabit_tpu.allreduce(a, SUM)
            digest.update(a.tobytes())

    # Exact int64 guard: classic (never codec'd) ops must stay exact at
    # any depth — a dropped/double-merged pipeline chunk is a hard
    # value error here, independent of the digest compare.
    size = 10_001
    a = (np.arange(size, dtype=np.int64) * (rank + 1)) % 97
    expect = np.zeros(size, np.int64)
    for r in range(world):
        expect += (np.arange(size, dtype=np.int64) * (r + 1)) % 97
    rabit_tpu.allreduce(a, SUM)
    np.testing.assert_array_equal(a, expect)
    digest.update(a.tobytes())

    if os.environ.get("RABIT_EXPECT_PIPE") == "1":
        from rabit_tpu import engine as engine_mod

        stats = engine_mod.get_engine().stats()
        ops = stats.get("counters", {}).get("pipe.ops", 0)
        # World-level consensus: hier's non-leader ranks legitimately
        # run no hop loop of their own (they park on the leader), so
        # the vacuity gate is "SOMEONE pipelined", not "everyone did".
        total = np.array([float(ops)])
        rabit_tpu.allreduce(total, SUM)
        assert total[0] > 0, (
            "RABIT_EXPECT_PIPE=1 but no rank ran the pipelined path "
            "(sum of pipe.ops == 0) — the parity run is vacuous")

    with open(f"{out}.r{rank}", "w") as f:
        f.write(digest.hexdigest())
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
