"""Worker: version-skew guard on a relaunched rank.

During iteration 1, rank 1 plants a stale-but-NEWER durable checkpoint
(version 9) in its own writer namespace, then dies at its kill-point
(run with RABIT_MOCK="1,1,1,0").  The relaunched life's
``load_checkpoint`` is warm-served the cluster-agreed version (1), sees
the newer valid version on its disk, and must raise the typed
``CheckpointSkewError`` carrying both versions instead of silently
serving stale state — this worker verifies the attributes and exits
with code 42 so the driver can assert the typed path fired.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ckpt import CheckpointSkewError, CheckpointStore


def main() -> None:
    ndata, niter = 500, 3
    try:
        rabit_tpu.init()
        rank = rabit_tpu.get_rank()
        world = rabit_tpu.get_world_size()
        version, model = rabit_tpu.load_checkpoint()
        start = model["iter"] if model is not None else 0

        for it in range(start, niter):
            if (rank == 1 and it == 1
                    and os.environ.get("RABIT_NUM_TRIAL", "0") == "0"):
                # Plant the skewed future version BEFORE this life's
                # kill-point (v1, seq1) fires below.
                CheckpointStore(os.environ["RABIT_CKPT_DIR"],
                                rank=1).persist(9, world, b"stale-future")
            a = np.arange(ndata, dtype=np.float32) + rank + it
            rabit_tpu.allreduce(a, rabit_tpu.MAX)
            obj = rabit_tpu.broadcast({"iter": it} if rank == 0 else None, 0)
            assert obj == {"iter": it}, obj
            rabit_tpu.checkpoint({"iter": it + 1})
        rabit_tpu.finalize()
    except CheckpointSkewError as e:
        assert e.disk_version == 9, e.disk_version
        assert 0 < e.agreed_version < 9, e.agreed_version
        print(f"ckpt_skew: typed skew raised as expected: {e}",
              flush=True)
        os._exit(42)


if __name__ == "__main__":
    main()
