"""Worker program: ring allreduce regression at ragged payload sizes.

Forces EVERY allreduce onto the ring path (crossover pinned to 0) and
runs payloads where ``len % world != 0`` — including ``len < world``,
where trailing ring blocks are zero-length — under a tiny reduce-buffer
budget so the sub-chunk loop (rewritten as an explicit chunk count) is
exercised at its edge cases.  Exact-op payloads (int SUM, f32 MAX) make
any dropped/misrouted block a hard value error.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.engine import pysocket
from rabit_tpu.ops import MAX, SUM

SIZES = [1, 2, 3, 5, 7, 13, 100, 1001, 65537]


def main() -> None:
    pysocket.TREE_RING_CROSSOVER_BYTES = 0  # every payload rides the ring
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    for size in SIZES:
        a = (np.arange(size, dtype=np.int64) * (rank + 1)) % 97
        expect = np.zeros(size, np.int64)
        for r in range(world):
            expect += (np.arange(size, dtype=np.int64) * (r + 1)) % 97
        rabit_tpu.allreduce(a, SUM)
        np.testing.assert_array_equal(a, expect, err_msg=f"sum size={size}")

        m = ((np.arange(size, dtype=np.float32) + rank) % 11.0)
        expect_m = np.max(
            [((np.arange(size, dtype=np.float32) + r) % 11.0)
             for r in range(world)], axis=0)
        rabit_tpu.allreduce(m, MAX)
        np.testing.assert_array_equal(m, expect_m, err_msg=f"max size={size}")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
