"""Worker program: ``rabit_sched=auto`` picks the tuning-cache winner.

Loads the same cache the engine loaded (RABIT_TUNE_DIR), runs one
sum-allreduce per cached payload point, and asserts via the obs
counters that the dispatch routed each op to the cached winner — the
runtime half of the tuner round-trip gate (tests/test_sched.py).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM
from rabit_tpu.sched import TuningCache


def main() -> None:
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    assert eng._sched_name == "auto", eng._sched_name
    cache = TuningCache.load(os.environ["RABIT_TUNE_DIR"])
    assert cache is not None, "worker must see the same cache as the test"
    points = sorted(int(s) for s in
                    cache.table["allreduce"][str(world)])
    expected = {}
    for nbytes in points:
        winner = cache.pick("allreduce", nbytes, world)
        assert eng._pick_schedule(nbytes, SUM).name == winner, \
            (nbytes, winner)
        nelem = max(nbytes // 8, 1)
        a = np.full(nelem, float(rank + 1), np.float64)
        rabit_tpu.allreduce(a, SUM)
        np.testing.assert_array_equal(
            a, np.full(nelem, world * (world + 1) / 2.0))
        expected[winner] = expected.get(winner, 0) + 1
    counters = eng.stats().get("counters", {})
    for winner, n in expected.items():
        got = counters.get(f"sched.pick.{winner}", 0)
        assert got >= n, (winner, n, got, counters)
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
