"""Worker program: even ranks C++ engine, odd ranks pure Python — verifies
wire-protocol interoperability in a single job.

Interop holds at the *base* protocol level: the robust variant prepends
consensus traffic to every collective, so every worker in a job must run
at the same protocol level (just as the reference requires all workers to
link the same engine flavour, src/engine.cc:20-28)."""
import os
import sys

tid = int(os.environ.get("RABIT_TASK_ID", "0"))
os.environ["RABIT_ENGINE"] = "base" if tid % 2 == 0 else "pysocket"
sys.argv = [sys.argv[0], "2000"]

sys.path.insert(0, os.path.dirname(__file__))
import check_basic  # noqa: E402

check_basic.main()
