"""Worker program: even ranks C++ native engine, odd ranks pure Python —
verifies wire-protocol interoperability in a single job."""
import os
import sys

tid = int(os.environ.get("RABIT_TASK_ID", "0"))
os.environ["RABIT_ENGINE"] = "native" if tid % 2 == 0 else "pysocket"
sys.argv = [sys.argv[0], "2000"]

sys.path.insert(0, os.path.dirname(__file__))
import check_basic  # noqa: E402

check_basic.main()
