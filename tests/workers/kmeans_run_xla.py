"""Worker: the kmeans *app* (kmeans.run) over the XLA engine — the full
TPU-native slice: staged device shard → device stats pass → stats
allreduce riding the device data plane → checkpoint via control plane.

argv: <data_pattern(%d)> <k> <max_iter> <out_prefix>
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)

import numpy as np

import rabit_tpu
from rabit_tpu.learn import kmeans, load_libsvm


def main() -> int:
    pattern, k, max_iter, out = (sys.argv[1], int(sys.argv[2]),
                                 int(sys.argv[3]), sys.argv[4])
    trial = int(os.environ.get("RABIT_NUM_TRIAL", "0") or 0)
    rabit_tpu.init(rabit_engine="xla",
                   rabit_inner_engine=os.environ.get("RABIT_INNER",
                                                     "pysocket"))
    rank = rabit_tpu.get_rank()
    # Optional death injection RABIT_KMEANS_DIE="rank:version": die just
    # before committing that checkpoint version (first life only) — the
    # survivors degrade mid-iteration, the relaunch resumes from the
    # checkpoint, and the next checkpoint boundary re-forms the device
    # plane; kmeans.run must then re-upload its device shard (epoch
    # change) and keep full numeric agreement.
    die = os.environ.get("RABIT_KMEANS_DIE")
    if die and trial == 0:
        die_rank, die_version = map(int, die.split(":"))
        orig_checkpoint = rabit_tpu.checkpoint

        def checkpoint_with_killpoint(model):
            if (rabit_tpu.get_rank() == die_rank
                    and rabit_tpu.version_number() + 1 >= die_version):
                os._exit(254)
            orig_checkpoint(model)

        rabit_tpu.checkpoint = checkpoint_with_killpoint
    data = load_libsvm(pattern, rank=rank)
    model = kmeans.run(data, num_cluster=k, max_iter=max_iter,
                       row_block=32)

    # all ranks must agree on the final model
    gathered = rabit_tpu.allgather(model.centroids.reshape(-1))
    for r in range(rabit_tpu.get_world_size()):
        np.testing.assert_allclose(gathered[r],
                                   model.centroids.reshape(-1), rtol=1e-5)
    if rank == 0:
        np.save(out + ".npy", model.centroids)
    rabit_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
