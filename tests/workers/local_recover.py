"""Worker: (global, local) checkpoint pair recovery with lazy prepare.

TPU-native equivalent of the reference's local-checkpoint test
(reference: test/local_recover.cc:115-135, test/local_recover.py): each
rank keeps per-rank local state that must survive its own death via ring
replication, and allreduce inputs are produced by lazy prepare_fun hooks
(skipped when results are replayed from cache).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    version, gmodel, lmodel = rabit_tpu.load_checkpoint(with_local=True)
    start = gmodel["iter"] if gmodel is not None else 0
    if start > 0:
        # The local model is this rank's own state, recovered from ring
        # replicas even if this rank just died.
        assert lmodel is not None, "local model lost"
        assert lmodel["rank"] == rank, lmodel
        np.testing.assert_allclose(
            lmodel["state"], np.full(4, rank * 100 + start, dtype=np.float64))

    for it in range(start, niter):
        a = np.empty(ndata, dtype=np.float32)

        def prep():
            a[:] = np.arange(ndata, dtype=np.float32) + rank + it

        rabit_tpu.allreduce(a, rabit_tpu.MAX, prepare_fun=prep)
        np.testing.assert_allclose(
            a, np.arange(ndata, dtype=np.float32) + world - 1 + it)

        b = np.full(ndata, float(rank + 1), dtype=np.float64)
        rabit_tpu.allreduce(b, rabit_tpu.SUM)
        np.testing.assert_allclose(b, world * (world + 1) / 2)

        local = {"rank": rank,
                 "state": np.full(4, rank * 100 + it + 1, dtype=np.float64)}
        rabit_tpu.checkpoint({"iter": it + 1}, local)

    rabit_tpu.tracker_print(
        f"local_recover rank {rank}/{world} done "
        f"(trial {os.environ.get('RABIT_NUM_TRIAL', '0')})")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
