"""Worker program: XLA engine with numeric self-verification.

Runs under the local launcher/tracker.  The control plane rendezvous goes
through the inner host engine; jax.Array collectives ride the XLA device
path (Gloo-backed CPU collectives in tests, ICI on TPU).  Self-verification
style follows the reference (reference: test/model_recover.cc:29-70).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)

import jax.numpy as jnp
import numpy as np

import rabit_tpu


def main() -> None:
    rabit_tpu.init(rabit_engine="xla",
                   rabit_inner_engine=os.environ.get("RABIT_INNER", "pysocket"))
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    assert world > 1, "check_xla expects a multi-process run"
    assert jax.process_count() == world, (jax.process_count(), world)

    # device-path allreduce SUM on a jax.Array
    x = jnp.arange(64, dtype=jnp.float32) + rank
    out = rabit_tpu.allreduce(x, rabit_tpu.SUM)
    expect = world * np.arange(64, dtype=np.float32) + world * (world - 1) / 2
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)

    # device-path allreduce MAX
    out = rabit_tpu.allreduce(jnp.full((8,), float(rank)), rabit_tpu.MAX)
    np.testing.assert_allclose(np.asarray(out), world - 1)

    # result of a device collective feeds the next one (stays on device)
    y = rabit_tpu.allreduce(out * 0 + (rank + 1), rabit_tpu.SUM)
    np.testing.assert_allclose(np.asarray(y), world * (world + 1) / 2)

    # numpy goes through the fault-tolerant host path
    a = np.arange(32, dtype=np.float64) + rank
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    np.testing.assert_allclose(
        a, world * np.arange(32, dtype=np.float64) + world * (world - 1) / 2)

    # device-path allgather
    g = rabit_tpu.allgather(jnp.array([rank, 2 * rank], dtype=jnp.int32))
    g = np.asarray(g)
    for r in range(world):
        assert (g[r] == [r, 2 * r]).all(), g

    # degraded mode: a failing device collective must fall back to the
    # fault-tolerant host transport (and keep returning device arrays)
    from rabit_tpu import engine as _engine_mod
    eng = _engine_mod.get_engine()
    orig = eng._device_collective
    # inject the realistic failure type: the degrade filter only catches
    # JaxRuntimeError/OSError (programming errors must propagate)
    eng._device_collective = lambda *a, **k: (_ for _ in ()).throw(
        jax.errors.JaxRuntimeError("injected device failure"))
    try:
        out = rabit_tpu.allreduce(jnp.full((16,), float(rank + 1)),
                                  rabit_tpu.SUM)
        assert isinstance(out, jax.Array)
        np.testing.assert_allclose(np.asarray(out),
                                   world * (world + 1) / 2)
        g2 = np.asarray(rabit_tpu.allgather(
            jnp.array([10 + rank], dtype=jnp.int32)))
        assert list(g2.reshape(-1)) == [10 + r for r in range(world)]
    finally:
        eng._device_collective = orig
        eng._degraded = False

    # programming errors must NOT degrade: they propagate to the caller
    eng._device_collective = lambda *a, **k: (_ for _ in ()).throw(
        TypeError("shape bug"))
    try:
        rabit_tpu.allreduce(jnp.zeros((4,)), rabit_tpu.SUM)
        raise AssertionError("TypeError was swallowed by degrade path")
    except TypeError:
        assert not eng._degraded, "programming error switched engine mode"
    finally:
        eng._device_collective = orig

    # control-plane object broadcast, any root
    for root in range(world):
        obj = {"root": root} if rank == root else None
        assert rabit_tpu.broadcast(obj, root) == {"root": root}

    # checkpoint trio through the control plane
    version, model = rabit_tpu.load_checkpoint()
    assert version == 0 and model is None
    rabit_tpu.checkpoint({"iter": 1, "rank0_said": "hi"})
    assert rabit_tpu.version_number() == 1
    # lazy variant: serialization deferred until a peer needs the payload
    rabit_tpu.lazy_checkpoint({"iter": 2})
    assert rabit_tpu.version_number() == 2
    version, model = rabit_tpu.load_checkpoint()
    assert version == 2 and model == {"iter": 2}, (version, model)

    rabit_tpu.tracker_print(f"check_xla rank {rank}/{world} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
