"""Worker program: bit-parity regression for one collective schedule.

The schedule matrix's worker (tests/test_sched.py): forces a schedule
via RABIT_SCHED, asserts the engine actually resolved that mode, and
runs the ragged/edge payload ladder from the ``ring_oddsize`` pattern —
zero-length, 1-item, odd sizes, and >chunk payloads under a tiny
reduce-buffer budget — with exact-arithmetic payloads (int SUM, f32/f64
SUM/MAX of small integers) so any dropped, misrouted or double-merged
block is a hard value error regardless of reduction order.  With
RABIT_WIRE_DTYPE=bf16 an extra f32-sum case runs whose values and sums
stay exactly representable in bfloat16, pinning the bf16-wire x
schedule composition bit-exactly.

argv[1] (optional) = the rabit_sched mode the engine must have resolved
(defaults to $RABIT_SCHED).  A forced schedule that does not APPLY at
this world/topology (e.g. swing at world 3, hier with one host group)
keeps the mode but dispatches through the static fallback — results
must be exact either way, which this worker pins.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu.ops import MAX, SUM

SIZES = [0, 1, 2, 3, 5, 7, 13, 100, 1001, 4097]


def main() -> None:
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    want = sys.argv[1] if len(sys.argv) > 1 else os.environ["RABIT_SCHED"]
    assert eng._sched_name == want, (eng._sched_name, want)

    for size in SIZES:
        a = (np.arange(size, dtype=np.int64) * (rank + 1)) % 97
        expect = np.zeros(size, np.int64)
        for r in range(world):
            expect += (np.arange(size, dtype=np.int64) * (r + 1)) % 97
        rabit_tpu.allreduce(a, SUM)
        np.testing.assert_array_equal(a, expect, err_msg=f"sum size={size}")

        # f32 MAX: order-free, exercises the float path on every dtype
        # branch of the schedules.
        m = ((np.arange(size, dtype=np.float32) + rank) % 11.0)
        expect_m = (np.max(
            [((np.arange(size, dtype=np.float32) + r) % 11.0)
             for r in range(world)], axis=0)
            if size else np.zeros(0, np.float32))
        rabit_tpu.allreduce(m, MAX)
        np.testing.assert_array_equal(m, expect_m,
                                      err_msg=f"max size={size}")

        # f64 SUM of small integers: exact in any reduction order, so
        # bit-exact vs the blocking tree baseline by construction.
        d = np.asarray((np.arange(size) * (rank + 2)) % 53, np.float64)
        expect_d = np.zeros(size, np.float64)
        for r in range(world):
            expect_d += ((np.arange(size) * (r + 2)) % 53).astype(
                np.float64)
        rabit_tpu.allreduce(d, SUM)
        np.testing.assert_array_equal(d, expect_d,
                                      err_msg=f"f64 sum size={size}")

    if os.environ.get("RABIT_WIRE_DTYPE") == "bf16":
        # Small integers: values and all partial sums (<= 7 per elem *
        # world 8 = 56) are exact in bfloat16's 8-bit mantissa, so the
        # halved-wire path must come out bit-exact too.
        for size in (1, 7, 1001, 4097):
            a = np.asarray((np.arange(size) + rank) % 8, np.float32)
            expect = np.zeros(size, np.float64)
            for r in range(world):
                expect += (np.arange(size) + r) % 8
            rabit_tpu.allreduce(a, SUM)
            np.testing.assert_array_equal(
                a, expect.astype(np.float32),
                err_msg=f"bf16 sum size={size}")

    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
