"""Worker: MIXED mode — jax.distributed initialized by the worker itself
(the pod-orchestration pattern) AND a tracker control plane present.

The engine must adopt the external JAX runtime for the device plane
while keeping the tracker-backed inner engine as the fault-tolerant
host transport: numpy ops ride the robust host engine (result replay,
checkpoints), jax.Array ops ride the device plane when the two rank
numberings align, and — MIXED_MODE=mismatch — a misaligned numbering
degrades EVERY rank to the host transport by consensus instead of
crashing or split-braining.

The engine registers with task_id = jax.process_index() automatically;
the test's tracker runs with RABIT_TRACKER_PIN_RANKS=1 so the
control-plane rank equals the device numbering.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
try:
    jax.config.update("jax_enable_recoverability", True)
except Exception:  # noqa: BLE001 — older jax
    pass

RANK = int(os.environ["MIXED_RANK"])
WORLD = int(os.environ["MIXED_WORLD"])
MODE = os.environ.get("MIXED_MODE", "ok")

jax.distributed.initialize(
    coordinator_address=os.environ["MIXED_COORD"],
    num_processes=WORLD, process_id=RANK)

import jax.numpy as jnp
import numpy as np

import rabit_tpu
from rabit_tpu import engine as engine_mod


def main() -> None:
    extra = {}
    if MODE == "mismatch":
        # deliberately misaligned control-plane identity: with pinning,
        # the tracker rank becomes WORLD-1-RANK while the device rank
        # stays RANK (rank (WORLD-1)/2 still matches itself — exactly
        # the asymmetry the consensus degrade exists for)
        extra["rabit_task_id"] = str(WORLD - 1 - RANK)
    rabit_tpu.init(rabit_engine="xla", rabit_inner_engine="pysocket",
                   **extra)
    eng = engine_mod.get_engine()
    assert rabit_tpu.get_world_size() == WORLD
    assert eng._adopted_jax, "tracker + pre-initialized JAX => mixed mode"
    my_rank = rabit_tpu.get_rank()
    if MODE == "ok":
        # pinning + automatic task_id registration align the numberings
        assert my_rank == RANK, (my_rank, RANK)
        assert not eng._degraded
        assert eng.mesh is not None
    elif MODE == "relaunch":
        # RABIT_RELAUNCH=1 (set by the test): a mixed-mode relaunch must
        # STILL be marked adopted (or its checkpoint-time _maybe_reform
        # ops would have no partner on the survivors) and must come up
        # degraded permanently — no init-time consensus, no reform.
        assert my_rank == RANK, (my_rank, RANK)
        assert eng._degraded and eng.mesh is None
    else:
        assert my_rank == WORLD - 1 - RANK, (my_rank, RANK)
        assert eng._degraded, "misaligned mesh must degrade by consensus"
        assert eng.mesh is None

    # numpy ops ride the fault-tolerant host engine in BOTH modes
    a = np.arange(8, dtype=np.float32) + my_rank
    out = rabit_tpu.allreduce(a, rabit_tpu.SUM)
    expect = np.arange(8, dtype=np.float32) * WORLD + sum(range(WORLD))
    np.testing.assert_allclose(a, expect)
    assert out is a

    # jax.Array op: device plane when aligned, host degrade otherwise
    x = jnp.full((16,), float(my_rank + 1))
    got = rabit_tpu.allreduce(x, rabit_tpu.MAX)
    np.testing.assert_allclose(np.asarray(got), float(WORLD))
    if MODE == "ok":
        assert eng.path_stats["device_ops"] >= 1 and eng.path_stats["host_ops"] == 0
    else:
        assert eng.path_stats["device_ops"] == 0 and eng.path_stats["host_ops"] >= 1

    # the host plane's checkpoint protocol is the point of mixed mode:
    # pure adopt has no fault-tolerant state at all
    model = {"iter": 3, "w": [float(my_rank)]}
    rabit_tpu.checkpoint(model)
    assert rabit_tpu.version_number() == 1
    ver, loaded = rabit_tpu.load_checkpoint()
    assert (ver, loaded) == (1, model)

    # object broadcast (any-root)
    obj = {"from": my_rank} if my_rank == 1 else None
    got = rabit_tpu.broadcast(obj, root=1)
    assert got == {"from": 1}

    rabit_tpu.finalize()
    print(f"MIXED-OK rank {my_rank}", flush=True)
    # skip jax's own racy atexit teardown of the gloo world (same
    # convention as adopt_worker.py)
    os._exit(0)


if __name__ == "__main__":
    main()
