"""Worker: global-checkpoint recovery with numeric self-verification.

TPU-native equivalent of the reference's recovery test program
(reference: test/model_recover.cc:29-124): every iteration runs a MAX
allreduce, a rotating-root broadcast and a SUM allreduce — each verified
against a locally computed expectation — then checkpoints.  Run under the
mock engine with kill-points (RABIT_MOCK) and the keepalive launcher to
exercise death/restart/replay at every collective.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    niter = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    version, model = rabit_tpu.load_checkpoint()
    start = model["iter"] if model is not None else 0
    assert version == start, (version, model)

    for it in range(start, niter):
        a = np.arange(ndata, dtype=np.float32) + rank + it
        rabit_tpu.allreduce(a, rabit_tpu.MAX)
        np.testing.assert_allclose(
            a, np.arange(ndata, dtype=np.float32) + world - 1 + it)

        root = it % world
        obj = {"iter": it, "root": root} if rank == root else None
        obj = rabit_tpu.broadcast(obj, root)
        assert obj == {"iter": it, "root": root}, obj

        b = np.ones(ndata, dtype=np.float64) * (rank + 1)
        rabit_tpu.allreduce(b, rabit_tpu.SUM)
        np.testing.assert_allclose(b, world * (world + 1) / 2)

        rabit_tpu.checkpoint({"iter": it + 1})
        assert rabit_tpu.version_number() == it + 1

    rabit_tpu.tracker_print(
        f"model_recover rank {rank}/{world} finished {niter} iters "
        f"(trial {os.environ.get('RABIT_NUM_TRIAL', '0')})")

    # traffic accounting for the routed-recovery test: record payload
    # bytes this rank SENT while serving recovery (0 when no one died)
    traffic_dir = os.environ.get("RABIT_TRAFFIC_DIR")
    if traffic_dir:
        from rabit_tpu import engine as _em

        eng = _em.get_engine()
        if hasattr(eng, "debug_routed_bytes"):
            path = os.path.join(traffic_dir, f"routed.{rank}")
            with open(path, "w") as f:
                f.write(str(eng.debug_routed_bytes()))
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
