"""Worker: pins the replay-cache semantics of the robust engines.

Rank 1 dies at version 0 seqno 1 (mock kill-point).  Its relaunched
life must be served seqno 0 from a survivor's cache with
``prepare_fun`` SKIPPED (the lazy-preparation contract,
engine/interface.py:67-88) and ``last_op_replayed`` True; the op it
rejoins mid-flight and every later op count as fresh.  On the
pure-Python robust engine the result cache is additionally asserted
non-empty within a version span and EMPTY right after each
``checkpoint()`` commit (seqnos restart per span).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import engine as engmod

NITER = 2


def main() -> None:
    trial = int(os.environ.get("RABIT_NUM_TRIAL", 0))
    rabit_tpu.init()
    eng = engmod.get_engine()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    version, model = rabit_tpu.load_checkpoint()
    start = model["iter"] if model is not None else 0
    assert version == start, (version, model)

    for it in range(start, NITER):
        calls = [0]
        a = np.empty(8, dtype=np.float64)

        def prep(it=it, calls=calls, a=a):
            calls[0] += 1
            a[:] = rank + it

        rabit_tpu.allreduce(a, rabit_tpu.MAX, prepare_fun=prep)  # seq 0
        # BIT-identical to the no-fault run: a replay serves the exact
        # cached bytes, so even the relaunched rank's value is equal,
        # not merely close.
        np.testing.assert_array_equal(a, np.full(8, world - 1.0 + it))
        if trial > 0 and rank == 1 and it == 0:
            # Relaunched rank: seq 0 completed before it rejoined, so the
            # result comes from a survivor's cache — prepare_fun must be
            # skipped and the replay flag honest.
            assert eng.last_op_replayed, "replayed op not flagged"
            assert calls[0] == 0, "prepare_fun ran on a replayed op"
        else:
            assert not eng.last_op_replayed, "fresh op flagged as replay"
            assert calls[0] == 1, calls

        b = np.full(8, float(rank + 1), dtype=np.float64)
        rabit_tpu.allreduce(b, rabit_tpu.SUM)  # seq 1 (the kill-point)
        np.testing.assert_array_equal(
            b, np.full(8, world * (world + 1) / 2))
        # The relaunched rank REJOINS seq 1 mid-flight (survivors could
        # not complete it without rank 1): a current-round fresh op.
        assert not eng.last_op_replayed, "mid-flight rejoin marked replay"

        if hasattr(eng, "_cache"):  # pyrobust: cache introspection
            assert len(eng._cache) > 0, "no results cached in the span"
        rabit_tpu.checkpoint({"iter": it + 1})
        assert rabit_tpu.version_number() == it + 1
        if hasattr(eng, "_cache"):
            assert len(eng._cache) == 0, "cache not cleared at commit"
            assert eng._seq == 0, "seqno not reset at commit"

    rabit_tpu.tracker_print(
        f"replay_cache rank {rank}/{world} trial {trial} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
