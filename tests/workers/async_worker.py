"""Worker program: async collective handle semantics.

Modes (argv[1]):

* ``parity``  — async + bucketed results must be BIT-identical to the
  blocking path on the same inputs (tree- and ring-sized members, mixed
  buckets, allgather, interleaved blocking ops).
* ``order``   — waiting handles out of issue order raises
  ``AsyncOrderError``; waiting in order afterwards still works.
* ``fusion``  — the bucket coalescer actually fuses (obs counters:
  bucket/member/byte totals, queue-depth gauge, overlap histogram).
* ``bf16``    — ``rabit_wire_dtype=bf16`` accuracy guard: f32
  sum-allreduce within bf16 tolerance; non-eligible ops stay exact.
* ``overlap`` — perf smoke: an async op completes while the caller
  computes; the overlap histogram records it.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import AsyncOrderError
from rabit_tpu.ops import MAX, SUM


def gen(i: int, size: int, dtype, rank: int) -> np.ndarray:
    rng = np.random.default_rng((i, size, rank))
    if np.issubdtype(np.dtype(dtype), np.floating):
        return rng.standard_normal(size).astype(dtype)
    return rng.integers(-1000, 1000, size).astype(dtype)


# (index, size, dtype, op): tree-sized (<=64KB), ring-sized (>64KB but
# bucket-eligible at <=1MB), and past-bucket members, mixed dtypes/ops
# so the coalescer must split buckets.
PARITY_OPS = [
    (0, 1, np.float32, SUM),
    (1, 777, np.float32, SUM),
    (2, 5000, np.float32, SUM),
    (3, 5000, np.float64, SUM),       # dtype flip -> new bucket
    (4, 20000, np.float32, SUM),      # 80KB: ring-sized, bucket-eligible
    (5, 1000, np.float32, MAX),       # op flip -> new bucket
    # three tree-class members whose CONCATENATION (144KB) crosses the
    # tree/ring threshold: the fused op must still ride the tree, or
    # float sums change order and bits (regression: fused dispatch)
    (10, 12000, np.float32, SUM),
    (11, 12000, np.float32, SUM),
    (12, 12000, np.float32, SUM),
    (6, 70000, np.float32, SUM),      # 280KB ring member, same bucket
    (7, 400000, np.float32, SUM),     # 1.6MB: past the bucket, solo async
    (8, 3000, np.int64, SUM),
]


def run_parity(rank: int) -> None:
    blocking = []
    for i, size, dtype, op in PARITY_OPS:
        a = gen(i, size, dtype, rank)
        rabit_tpu.allreduce(a, op)
        blocking.append(a)
    # Async pass over identical inputs: issue everything, then wait in
    # order — buckets fuse wherever op/dtype/size allow.
    arrays = [gen(i, size, dtype, rank) for i, size, dtype, op in PARITY_OPS]
    handles = [rabit_tpu.allreduce_async(a, op)
               for a, (_i, _s, _d, op) in zip(arrays, PARITY_OPS)]
    for h, a, b in zip(handles, arrays, blocking):
        out = h.wait()
        assert out is a, "allreduce_async must resolve to the caller's array"
        assert a.tobytes() == b.tobytes(), \
            f"rank {rank}: async result differs from blocking (bit-level)"
    # Interleaving: async issues, a blocking op (which fences), then
    # waits — still bit-identical, still ordered.
    a0, a1 = gen(20, 4000, np.float32, rank), gen(21, 6000, np.float32, rank)
    b0, b1 = a0.copy(), a1.copy()
    h0 = rabit_tpu.allreduce_async(a0, SUM)
    h1 = rabit_tpu.allreduce_async(a1, SUM)
    mid = gen(22, 100, np.float64, rank)
    mid_b = mid.copy()
    rabit_tpu.allreduce(mid, SUM)
    assert h0.wait().tobytes() == rabit_tpu.allreduce(b0, SUM).tobytes()
    assert h1.wait().tobytes() == rabit_tpu.allreduce(b1, SUM).tobytes()
    assert mid.tobytes() == rabit_tpu.allreduce(mid_b, SUM).tobytes()
    # allgather_async parity.
    g = gen(23, 257, np.float32, rank)
    hg = rabit_tpu.allgather_async(g.copy())
    assert hg.wait().tobytes() == rabit_tpu.allgather(g).tobytes()
    # fuse=False (eager lone-op dispatch) interleaved with a bucketed
    # stream: order and bits must both hold.
    e0 = gen(24, 500, np.float32, rank)
    e1 = gen(25, 800, np.float32, rank)
    e2 = gen(26, 500, np.float32, rank)
    h0 = rabit_tpu.allreduce_async(e0, SUM)
    h1 = rabit_tpu.allreduce_async(e1, SUM, fuse=False)
    h2 = rabit_tpu.allreduce_async(e2, SUM)
    for h, i, size in ((h0, 24, 500), (h1, 25, 800), (h2, 26, 500)):
        b = gen(i, size, np.float32, rank)
        rabit_tpu.allreduce(b, SUM)
        assert h.wait().tobytes() == b.tobytes()


def run_order(rank: int) -> None:
    a0 = gen(0, 100, np.float32, rank)
    a1 = gen(1, 100, np.float32, rank)
    ref = a0.copy()
    h0 = rabit_tpu.allreduce_async(a0, SUM)
    h1 = rabit_tpu.allreduce_async(a1, SUM)
    try:
        h1.wait()
    except AsyncOrderError:
        pass
    else:
        raise AssertionError("out-of-order wait() must raise")
    # In-order waits still succeed after the rejected attempt.
    h0.wait()
    h1.wait()
    h0.wait()  # re-wait is idempotent
    # Values match the blocking path bit-for-bit, whatever schedule the
    # dispatch picked (a fixed sequential-order expectation would pin
    # the tree's merge order and reject valid schedules).
    rabit_tpu.allreduce(ref, SUM)
    np.testing.assert_array_equal(a0, ref)


def run_fusion(rank: int) -> None:
    from rabit_tpu import engine as engine_mod

    world = rabit_tpu.get_world_size()
    nops, size = 8, 1000
    arrays = [np.full(size, float(rank + 1 + i), np.float32)
              for i in range(nops)]
    handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrays]
    for i, h in enumerate(handles):
        out = h.wait()
        np.testing.assert_array_equal(
            out, np.full(size, world * (world + 1) / 2.0 + world * i,
                         np.float32))
    stats = engine_mod.get_engine().stats()
    c = stats["counters"]
    assert c.get("async.ops") == nops, c
    assert c.get("async.fused.buckets") == 1, c
    assert c.get("async.fused.members") == nops, c
    assert c.get("async.fused.bytes") == nops * size * 4, c
    assert "async.queue_depth" in stats["gauges"], stats["gauges"]
    h = stats["histograms"].get("async.overlap.seconds")
    assert h and h["count"] == nops, h


def run_bf16(rank: int) -> None:
    world = rabit_tpu.get_world_size()
    for size in (500, 100000):  # tree- and ring-sized
        a = (1.0 + 0.5 * gen(size, size, np.float64, rank) ** 2).astype(
            np.float32)
        exact = np.zeros(size, np.float64)
        for r in range(world):
            exact += (1.0 + 0.5 * gen(size, size, np.float64, r) ** 2
                      ).astype(np.float32).astype(np.float64)
        rabit_tpu.allreduce(a, SUM)
        rel = np.abs(a.astype(np.float64) - exact) / exact
        assert rel.max() < 0.05, (size, rel.max())
        # and the wire dtype is actually lossy (a pass-through f32 sum
        # of these irrational values would be closer than bf16 eps)
        assert rel.max() > 1e-6, (size, rel.max())
    # Non-eligible ops stay exact: f32 MAX and f64 SUM of integers.
    m = gen(3, 1000, np.float32, rank)
    rabit_tpu.allreduce(m, MAX)
    expect = np.max([gen(3, 1000, np.float32, r) for r in range(world)],
                    axis=0)
    np.testing.assert_array_equal(m, expect)
    d = np.full(100, float(rank + 1), np.float64)
    rabit_tpu.allreduce(d, SUM)
    np.testing.assert_array_equal(d, np.full(100, world * (world + 1) / 2.0))
    # Async parity under the lossy wire: fused/async must be
    # bit-identical to blocking-with-bf16 — including the member sizes
    # whose bf16 TRANSPORT flips the solo tree/ring choice (100KB f32 ->
    # 50KB transport -> tree; 200KB -> 100KB transport -> ring).
    cases = [(30, 2000), (31, 2000), (32, 25000), (33, 50000)]
    blocking = []
    for i, size in cases:
        a = gen(i, size, np.float32, rank)
        rabit_tpu.allreduce(a, SUM)
        blocking.append(a)
    arrays = [gen(i, size, np.float32, rank) for i, size in cases]
    handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrays]
    for h, b in zip(handles, blocking):
        assert h.wait().tobytes() == b.tobytes(), \
            "bf16 async result differs from bf16 blocking (bit-level)"


def run_overlap(rank: int) -> None:
    from rabit_tpu import engine as engine_mod

    world = rabit_tpu.get_world_size()
    a = np.full(1 << 16, float(rank), np.float32)  # 256KB
    # fuse=False: a lone bucketed op would sit unsent until wait() and
    # overlap nothing — the eager path is what this smoke test times.
    h = rabit_tpu.allreduce_async(a, SUM, fuse=False)
    # Host compute the progress thread overlaps with the wire op.
    acc = 0.0
    for _ in range(20):
        acc += float(np.square(np.arange(1 << 14, dtype=np.float64)).sum())
    out = h.wait()
    np.testing.assert_array_equal(
        out, np.full(1 << 16, world * (world - 1) / 2.0, np.float32))
    stats = engine_mod.get_engine().stats()
    hist = stats["histograms"].get("async.overlap.seconds")
    assert hist and hist["count"] >= 1 and hist["max"] >= 0.0, hist
    assert acc > 0


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "parity"
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    {"parity": run_parity, "order": run_order, "fusion": run_fusion,
     "bf16": run_bf16, "overlap": run_overlap}[mode](rank)
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
