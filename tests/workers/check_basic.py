"""Worker program: numeric self-verification of the base collectives.

Modeled on the reference's test style — each collective's result is checked
against a locally computed expectation (reference: test/model_recover.cc:29-70).
Exits non-zero on any mismatch.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    ndata = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    # allreduce MAX: buf[i] = rank + i  -> expect world-1 + i
    a = np.arange(ndata, dtype=np.float32) + rank
    rabit_tpu.allreduce(a, rabit_tpu.MAX)
    expect = np.arange(ndata, dtype=np.float32) + world - 1
    np.testing.assert_allclose(a, expect)

    # allreduce SUM: buf[i] = rank + i  -> expect sum_r(r) + world*i
    a = np.arange(ndata, dtype=np.float32) + rank
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    expect = world * np.arange(ndata, dtype=np.float32) + world * (world - 1) / 2
    np.testing.assert_allclose(a, expect)

    # large allreduce (forces the ring path): SUM of ones
    big = np.ones(300_000, dtype=np.float64) * (rank + 1)
    rabit_tpu.allreduce(big, rabit_tpu.SUM)
    np.testing.assert_allclose(big, world * (world + 1) / 2)

    # allreduce MIN, int dtype
    b = np.full(7, rank + 3, dtype=np.int32)
    rabit_tpu.allreduce(b, rabit_tpu.MIN)
    assert (b == 3).all(), b

    # zero-size allreduce is a (collective) no-op on every rank
    z = np.empty(0, dtype=np.float64)
    rabit_tpu.allreduce(z, rabit_tpu.SUM)
    assert z.size == 0

    # broadcast from every root, object payload
    for root in range(world):
        obj = {"root": root, "blob": list(range(root + 1))} if rank == root else None
        got = rabit_tpu.broadcast(obj, root)
        assert got == {"root": root, "blob": list(range(root + 1))}, got

    # multi-chunk broadcast (payload >> the 256 KB pipeline chunk)
    big_blob = (np.arange(1 << 18, dtype=np.int64) * 3 + 1
                if rank == 1 else None)  # 2 MB
    got = rabit_tpu.broadcast(big_blob, 1)
    assert (got == np.arange(1 << 18, dtype=np.int64) * 3 + 1).all()

    # allgather
    g = rabit_tpu.allgather(np.array([rank, rank * 2], dtype=np.int64))
    for r in range(world):
        assert (g[r] == [r, 2 * r]).all(), g

    rabit_tpu.tracker_print(f"check_basic rank {rank}/{world} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
