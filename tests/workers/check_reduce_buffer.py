"""Worker program: bounded-scratch collectives under rabit_reduce_buffer.

Runs allreduces far larger than the configured budget, verifies the
numeric results, and asserts the engine's per-op scratch peak stayed
within the budget (reference: reduce_buffer chunking,
src/allreduce_base.cc:31,117-132).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu
from rabit_tpu import engine as engine_mod
from rabit_tpu.utils.units import parse_byte_size


def main() -> None:
    if os.environ.get("RABIT_MIXED_BUDGETS"):
        # Every worker picks a different budget: per-link byte streams
        # are chunk-size-independent, so mixed budgets must interoperate.
        choices = ["64KB", "300KB", "1MB", "256MB"]
        budget = parse_byte_size(
            choices[int(os.environ.get("RABIT_TASK_ID", 0)) % len(choices)])
        rabit_tpu.init(rabit_reduce_buffer=str(budget))
    else:
        budget = parse_byte_size(os.environ["RABIT_REDUCE_BUFFER"])
        rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    # SUM allreduce, payload >> budget.  world==2 rides the (chunked)
    # tree, world>2 the (sub-chunked) ring.
    n = 1 << 20  # 8 MB of f64
    a = np.full(n, float(rank + 1), dtype=np.float64)
    a[::7] += rank  # non-uniform so ordering bugs shift values
    expect = np.full(n, world * (world + 1) / 2.0, dtype=np.float64)
    expect[::7] += world * (world - 1) / 2.0
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    np.testing.assert_allclose(a, expect)

    # Custom reducer: always the tree path, chunked at any world size.
    b = np.full(1 << 18, float(rank), dtype=np.float64)  # 2 MB

    def maxsum(dst: np.ndarray, src: np.ndarray) -> None:
        dst += src

    rabit_tpu.allreduce_custom(b, maxsum)
    np.testing.assert_allclose(b, world * (world - 1) / 2.0)

    eng = engine_mod.get_engine()
    if hasattr(eng, "debug_scratch_peak_bytes"):  # native
        peak = eng.debug_scratch_peak_bytes()
    else:  # pysocket
        peak = eng.scratch_peak_bytes
    assert 0 < peak <= budget, (
        f"rank {rank}: scratch peak {peak} outside (0, {budget}]")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
