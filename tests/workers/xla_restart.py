"""Worker program: XLA-engine death + relaunch + checkpoint resume.

Proves the device-plane fault story end-to-end (the iteration-granularity
contract documented in engine/xla.py): rank 1 dies mid-run before its
iteration-2 device collective; the survivors' Gloo collective fails, they
degrade to the fault-tolerant host transport, and the robust inner
protocol blocks until the keepalive launcher restarts rank 1.  The
restarted incarnation (RABIT_NUM_TRIAL > 0) comes up degraded — the
original mesh died with it — loads the version-2 checkpoint through
recovery serving, and the job finishes with verified numerics
(reference recovery contract: src/allreduce_robust.cc:73-105).

With device-plane re-formation enabled (the default), the first
checkpoint after the world re-forms tears down the broken JAX group and
builds a fresh one, so the tail of the run executes on the device mesh
again — asserted below via the engine's path counters (the reference's
recovered jobs likewise return to full speed,
reference: src/allreduce_robust.cc:426-453).  RABIT_DEVICE_REFORM=0
runs the round-2 permanently-degraded contract instead.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)

import jax.numpy as jnp
import numpy as np

import rabit_tpu

NITER = 4


def _die_plan() -> dict[int, int]:
    """RABIT_XLA_DIE="rank:iter[;rank:iter...]" -> {rank: die_iter}
    ("none" = nobody dies, e.g. the whole-job-restart scenario).

    RABIT_XLA_DIE_FORMATION=<rank> marks a formation-window victim (the
    ENGINE kills it inside _init_jax_distributed, before any iteration)
    recorded here as die_iter = -1: never killed by the loop below, but
    its relaunch must pass the victim assertions and the run must end
    with a re-formed device plane."""
    plan = os.environ.get("RABIT_XLA_DIE", "1:2")
    out: dict[int, int] = {}
    if plan not in ("", "none"):
        for part in plan.split(";"):
            r, it = part.split(":")
            out[int(r)] = int(it)
    form = os.environ.get("RABIT_XLA_DIE_FORMATION")
    if form not in (None, ""):
        out[int(form)] = -1
    # RABIT_XLA_DIE_ON_REFORM=<rank>: die the moment the device plane
    # RE-FORMS (first epoch change this incarnation observes) — the
    # victim dies inside the replayed post-reform round, exercising the
    # stale-group/replayed-round branches (engine/xla.py _maybe_reform).
    # die_iter = -2: never triggered by the iteration check below.
    reform = os.environ.get("RABIT_XLA_DIE_ON_REFORM")
    if reform not in (None, ""):
        out[int(reform)] = -2
    return out


def main() -> None:
    trial = int(os.environ.get("RABIT_NUM_TRIAL", 0))
    die = _die_plan()
    # Simulate a platform restart with a clean environment: the engine
    # must detect the mid-job relaunch via the tracker's relaunched flag,
    # not via these launcher-provided variables.
    os.environ.pop("RABIT_NUM_TRIAL", None)
    os.environ.pop("RABIT_RELAUNCH", None)
    # Whole-job-restart scenario: every rank believes it is a mid-job
    # relaunch (long-lived tracker, coordinated platform restart) — all
    # come up degraded, and the first checkpoint boundary must re-form
    # the device plane from nothing.
    forced = os.environ.get("RABIT_XLA_FORCE_RELAUNCH") == "1"
    if forced:
        os.environ["RABIT_RELAUNCH"] = "1"
    rabit_tpu.init(rabit_engine="xla",
                   rabit_inner_engine=os.environ.get("RABIT_INNER", "native"),
                   rabit_timeout_sec="30")
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    assert world > 1

    version, model = rabit_tpu.load_checkpoint()
    state = float(model) if version > 0 else 0.0
    if trial > 0:
        assert rank in die, f"rank {rank} restarted but was not a victim"
        # >= not ==: a watchdog restart (trial unchanged) may hit a later
        # incarnation that already checkpointed past its kill-point.
        assert version >= die[rank], (version, die[rank])

    reform_victim = (die.get(rank) == -2 and trial == 0)
    epoch0 = rabit_tpu.device_epoch()
    for it in range(version, NITER):
        if rank in die and trial == 0 and it == die[rank]:
            os._exit(254)  # the keepalive launcher's restart code
        if reform_victim and rabit_tpu.device_epoch() != epoch0:
            # the plane just re-formed under this incarnation: die inside
            # the replayed round, before contributing this iteration
            os._exit(254)
        # Device-plane allreduce: real Gloo collective until the death,
        # host-degraded afterwards (both return jax.Array).
        x = jnp.full((32,), float(rank + it), dtype=jnp.float32)
        out = rabit_tpu.allreduce(x, rabit_tpu.SUM)
        expect = float(sum(r + it for r in range(world)))
        np.testing.assert_allclose(np.asarray(out), expect)
        assert isinstance(out, jax.Array)
        state += expect
        # Host-plane op in the same iteration (stays fault-tolerant).
        h = np.array([float(rank == it)], dtype=np.float64)
        rabit_tpu.allreduce(h, rabit_tpu.MAX)
        assert h[0] == (1.0 if it < world else 0.0), (rank, it, h)
        rabit_tpu.checkpoint(state)

    assert state == float(sum(sum(r + it for r in range(world))
                              for it in range(NITER))), state

    reform_on = os.environ.get("RABIT_DEVICE_REFORM", "1") not in (
        "0", "false", "no")
    a_death_happened = any(it < NITER for it in die.values())
    if reform_on and (a_death_happened or forced):
        from rabit_tpu import engine as engmod

        eng = engmod.get_engine()
        assert rabit_tpu.device_epoch() >= 1, (
            "device plane never re-formed after the death")
        before = eng.path_stats["device_ops"]
        out = rabit_tpu.allreduce(jnp.ones(8, jnp.float32), rabit_tpu.SUM)
        np.testing.assert_allclose(np.asarray(out), float(world))
        assert eng.path_stats["device_ops"] == before + 1, (
            "post-reform collective did not ride the device mesh")
    rabit_tpu.tracker_print(
        f"xla_restart rank {rank}/{world} trial {trial} "
        f"epoch {rabit_tpu.device_epoch()} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
