"""Worker: ADOPT mode at world > 1 — JAX distributed runtime initialized
by the worker itself (the pod-orchestration pattern), no tracker.

The engine must adopt JAX's rank/world identity, route numpy buffers
through device reductions while preserving the in-place contract, ship
byte/object broadcasts over the device collectives
(_device_byte_broadcast), and — mode=peerdeath — surface a peer's death
as the documented RuntimeError (no host transport to degrade to).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
try:
    jax.config.update("jax_enable_recoverability", True)
except Exception:  # noqa: BLE001 — older jax
    pass

RANK = int(os.environ["ADOPT_RANK"])
WORLD = int(os.environ["ADOPT_WORLD"])
MODE = os.environ.get("ADOPT_MODE", "ok")

jax.distributed.initialize(
    coordinator_address=os.environ["ADOPT_COORD"],
    num_processes=WORLD, process_id=RANK)

import jax.numpy as jnp
import numpy as np

import rabit_tpu


def main() -> None:
    rabit_tpu.init(rabit_engine="xla")
    assert rabit_tpu.get_rank() == RANK, (rabit_tpu.get_rank(), RANK)
    assert rabit_tpu.get_world_size() == WORLD

    # numpy in-place semantics via device reduction
    a = np.arange(8, dtype=np.float32) + RANK
    out = rabit_tpu.allreduce(a, rabit_tpu.SUM)
    expect = (np.arange(8, dtype=np.float32) * WORLD
              + sum(range(WORLD)))
    np.testing.assert_allclose(a, expect)
    assert out is a, "numpy allreduce must fill the caller's buffer"

    # jax.Array device path
    x = jnp.full((16,), float(RANK + 1))
    out = rabit_tpu.allreduce(x, rabit_tpu.MAX)
    np.testing.assert_allclose(np.asarray(out), float(WORLD))

    # object broadcast -> _device_byte_broadcast round trip (root 1:
    # any-root contract), with a payload big enough to exercise the
    # pow2-padded chunking
    obj = {"weights": list(range(500)), "from": RANK} if RANK == 1 else None
    got = rabit_tpu.broadcast(obj, root=1)
    assert got == {"weights": list(range(500)), "from": 1}

    if MODE == "peerdeath":
        if RANK == 1:
            os._exit(7)  # die hard, mid-job
        try:
            for _ in range(50):
                rabit_tpu.allreduce(jnp.ones(4), rabit_tpu.SUM)
            print(f"ADOPT-NORAISE rank {RANK}", flush=True)
            os._exit(1)
        except RuntimeError as e:
            assert "no host transport" in str(e), e
            print(f"ADOPT-RAISED rank {RANK}", flush=True)
            os._exit(0)  # contract satisfied; skip collective teardown

    rabit_tpu.finalize()
    print(f"ADOPT-OK rank {RANK}", flush=True)
    # The engine owns no teardown in adopt mode (the runtime is the
    # orchestration's).  jax's own atexit shutdown races under
    # recoverable clients (the shutdown barrier only blocks
    # non-recoverable tasks, so the leader can exit before a follower's
    # ShutdownTask RPC lands -> client.h:80 fatal) — skip it; process
    # teardown is the platform's job in this mode.
    os._exit(0)


if __name__ == "__main__":
    main()
