"""Worker: Python custom reducers (allreduce_custom) with numeric
self-verification — runs on any engine (pysocket tree-folds in Python;
native calls back from the C++ tree)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import rabit_tpu


def main() -> None:
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()

    # row-wise argmax carrying an index payload: rows are (value, index)
    buf = np.zeros((4, 2), np.float64)
    calls = []

    def argmax_reduce(dst, src):
        calls.append(1)
        take = src[:, 0] > dst[:, 0]
        dst[take] = src[take]

    def prepare():
        for i in range(4):
            peak = 100.0 + i if rank == i % world else float(rank)
            buf[i] = (peak, rank)

    rabit_tpu.allreduce_custom(buf, argmax_reduce, prepare_fun=prepare)
    for i in range(4):
        assert buf[i, 0] == 100.0 + i, buf
        assert int(buf[i, 1]) == i % world, buf
    # leaf ranks of the tree never merge locally; the root always does
    if rank == 0:
        assert calls, "reducer never invoked on the root"

    # product via custom fn matches the builtin PROD op
    a = np.full(8, 1.0 + rank, np.float64)
    rabit_tpu.allreduce_custom(a, lambda d, s: np.multiply(d, s, out=d))
    expect = np.prod([1.0 + r for r in range(world)])
    np.testing.assert_allclose(a, expect, rtol=1e-12)

    rabit_tpu.tracker_print(f"custom_reduce_py rank {rank}/{world} OK")
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
