"""Causal tracing & postmortem plane tests (doc/observability.md
"Causal tracing & postmortem").

Fast unit coverage for the deterministic sampling decision, the
bounded hop buffer, the tracker-side assembler (skew-corrected
cross-rank timelines, binding/critical-path verdicts, link cost fold,
Chrome-trace schema), the always-on flight recorder (ring bounds,
atomic persistence, in-flight op semantics), the serve-SLO burn math
and the shard-level fold equality for the new sections — plus
distributed gates: a world-2 end-to-end ``/trace`` scrape and a
world-2 crash round proving flight records persist on both an injected
LinkError and a SIGTERM.  The world-4 SIGKILL reconstruction gate is
the slow ``tools/soak.py --postmortem``.
"""
import json
import os
import pathlib
import sys
import threading
import time
import urllib.request

import pytest

from rabit_tpu import obs
from rabit_tpu.obs import export as obs_export

pytestmark = pytest.mark.trace

REPO = pathlib.Path(__file__).resolve().parents[1]
VICTIM = str(REPO / "tests" / "workers" / "postmortem_victim.py")


def _hop(seq, hop, peer, t0, t1, *, epoch=0, version=0,
         kind="allreduce", phase="hop", nbytes=1024):
    """One wire-layout hop record (obs.trace.HOP_FIELDS)."""
    return [seq, epoch, version, kind, hop, peer, phase, nbytes, t0, t1]


# ------------------------------------------------------------- sampling
def test_trace_sampled_deterministic():
    assert not any(obs.trace_sampled(s, 0) for s in range(100))
    assert not any(obs.trace_sampled(s, -4) for s in range(100))
    picked = [s for s in range(100) if obs.trace_sampled(s, 8)]
    assert picked == list(range(0, 100, 8))
    # every rank computes the same decision from the same seqno — the
    # property that makes cross-rank assembly possible at all
    assert all(obs.trace_sampled(s, 1) for s in range(10))


# ----------------------------------------------------------- hop buffer
def test_hop_buffer_bounds_and_drain():
    hb = obs.HopBuffer(capacity=4)
    for i in range(6):
        hb.add(i, 0, 0, "allreduce", 0, 1, "hop", 64, 1.0, 1.1)
    assert len(hb) == 4 and hb.dropped == 2
    recs = hb.drain()
    assert len(recs) == 4 and len(hb) == 0
    assert recs[0][:4] == [0, 0, 0, "allreduce"]
    assert hb.drain() == []


# ------------------------------------------------------------ assembler
def test_assembler_skew_corrected_cross_rank_timeline():
    """Synthetic skewed timeline: rank 1's clock runs 5 s behind the
    tracker's.  Raw timestamps interleave wrongly; with offset samples
    folded in, the corrected timeline restores the causal hop order and
    the binding names the slow link."""
    ta = obs.TraceAssembler()
    # tracker_clock - rank_clock: rank 0 in sync, rank 1 is -5s skewed
    for _ in range(5):
        ta.note_offset(0, 0.0)
        ta.note_offset(1, 5.0)
    assert ta.offset(1) == pytest.approx(5.0)
    # true order: r0 hop0 100.0-100.1 -> r1 hop1 100.12-100.42 (slow)
    ta.add(0, [_hop(0, 0, 1, 100.0, 100.1)], world=2)
    ta.add(1, [_hop(0, 1, 0, 95.12, 95.42)], world=2)  # skewed clock
    tl = ta.timeline()
    assert [(d["rank"], d["hop"]) for d in tl] == [(0, 0), (1, 1)]
    assert tl[1]["t0"] == pytest.approx(100.12)
    crit = ta.critical_path()
    assert crit["rank"] == 1 and crit["link"] == "1->0"
    assert crit["sec"] == pytest.approx(0.30)
    assert ta.bound_by().startswith("link 1->0")


def test_assembler_groups_by_op_key_and_bounds_window():
    ta = obs.TraceAssembler(max_ops=4)
    for seq in range(10):
        ta.add(0, [_hop(seq, 0, 1, 10.0 + seq, 10.1 + seq)])
    assert ta.assembled == 10 and len(ta.ops()) == 4
    # same seq, different version: distinct ops (the span-key contract)
    ta.add(0, [_hop(9, 0, 1, 30.0, 30.1, version=7)])
    assert (0, 7, 9, "allreduce") in ta.ops()
    # link costs fold over everything ever ingested, not the window
    costs = ta.link_costs()
    assert costs["0->1"]["n"] == 11
    # garbage records are skipped, never raise
    before = ta.records
    ta.add(0, [["junk"], None, 13, {"seq": 1}])
    ta.add(0, "not a list")
    assert ta.records == before


def test_assembler_chrome_trace_schema():
    """The /trace export must be a valid Chrome Trace Event Format
    document (Perfetto-loadable): a traceEvents array whose "X" slices
    carry name/cat/pid/tid/ts/dur and whose per-rank process_name
    metadata rides "M" events."""
    ta = obs.TraceAssembler()
    ta.add(0, [_hop(0, 0, 1, 100.0, 100.1),
               _hop(0, 0, -1, 99.9, 100.0, phase="encode")])
    ta.add(1, [_hop(0, 1, 0, 100.1, 100.3)])
    doc = ta.chrome()
    assert set(doc) >= {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    assert json.loads(json.dumps(doc)) == doc  # JSON-serializable
    meta = [e for e in events if e["ph"] == "M"]
    slices = [e for e in events if e["ph"] == "X"]
    assert {e["pid"] for e in meta} == {0, 1}
    assert all(e["name"] == "process_name" for e in meta)
    assert len(slices) == 3
    for e in slices:
        assert {"name", "cat", "ph", "pid", "tid", "ts", "dur",
                "args"} <= set(e)
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert e["dur"] > 0
    names = {e["name"] for e in slices}
    assert "allreduce hop0" in names and "encode" in names
    # empty assembler: still a loadable document
    assert obs.TraceAssembler().chrome()["traceEvents"] == []


def test_assembler_report_shape():
    ta = obs.TraceAssembler()
    ta.add(0, [_hop(3, 0, 1, 10.0, 10.2)])
    rep = ta.report()
    assert rep["ops_assembled"] == 1 and rep["records"] == 1
    assert rep["last_op"]["key"] == [0, 0, 3, "allreduce"]
    assert rep["last_op"]["critical"]["link"] == "0->1"
    assert json.loads(json.dumps(rep)) == rep


# ------------------------------------------------------ flight recorder
def test_flight_recorder_ring_inflight_and_persist(tmp_path):
    fr = obs.FlightRecorder(capacity=8)
    fr.op_begin("allreduce", 5, 1, 2, 4096)
    assert fr.inflight["seq"] == 5
    fr.op_end()
    assert fr.inflight is None  # success clears it...
    fr.op_begin("allreduce", 6, 1, 2, 4096)
    fr.note("link_error", rank=0, peer=1, error="LinkError")
    # ...a fault path persists with the op still armed
    path = fr.persist(str(tmp_path), 0, "link_error", peer=1,
                      job="j", world=2, skipped=None)
    assert path and os.path.basename(path) == "flight.rank0.json"
    recs = obs.load_flight_records(str(tmp_path))
    assert len(recs) == 1
    rec = recs[0]
    assert rec["reason"] == "link_error" and rec["rank"] == 0
    assert rec["inflight"]["seq"] == 6 and rec["peer"] == 1
    assert "skipped" not in rec  # None-valued meta dropped
    assert any(e["name"] == "link_error" for e in rec["events"])
    # ring stays bounded
    for i in range(50):
        fr.note("spam", i=i)
    assert len(fr.ring) == 8 and fr.ring.dropped > 0
    # last writer wins (atomic replace, no partial state)
    fr.persist(str(tmp_path), 0, "sigterm")
    recs = obs.load_flight_records(str(tmp_path))
    assert len(recs) == 1 and recs[0]["reason"] == "sigterm"


def test_flight_recorder_persist_best_effort(tmp_path):
    fr = obs.FlightRecorder()
    bad = tmp_path / "file"
    bad.write_text("x")  # a FILE where a directory is needed
    assert fr.persist(str(bad), 0, "abort") is None
    assert fr.persists == 0
    # malformed artifacts are skipped by the loader
    (tmp_path / "flight.rank7.json").write_text("{ torn")
    assert obs.load_flight_records(str(tmp_path)) == []
    assert obs.load_flight_records(str(tmp_path / "missing")) == []


# -------------------------------------------------------- serve SLO math
def test_serve_slo_burn_math_and_associativity():
    def row(ok=0, shed=0, timeout=0, draining=0):
        # Same shape as a LiveTable row: flat serve.requests.* counters.
        return {"counters": {"serve.requests.ok": ok,
                             "serve.requests.shed": shed,
                             "serve.requests.timeout": timeout,
                             "serve.requests.draining": draining}}

    assert obs.serve_slo({}) is None
    assert obs.serve_slo({"0": {"counters": {}}}) is None
    # 1 bad in 100 at 99%: the whole budget is burning, none left
    slo = obs.serve_slo({"0": row(ok=99, shed=1)})
    assert slo["burn_rate"] == pytest.approx(1.0)
    assert slo["budget_remaining"] == pytest.approx(0.0)
    # draining is an orderly leave, not an SLO violation
    healthy = obs.serve_slo({"0": row(ok=99, draining=1)})
    assert healthy["burn_rate"] == 0.0
    assert healthy["budget_remaining"] == 1.0
    # burning faster than 1x clamps the remaining budget at 0
    hot = obs.serve_slo({"0": row(ok=90, timeout=10)})
    assert hot["burn_rate"] == pytest.approx(10.0)
    assert hot["budget_remaining"] == 0.0
    # associative: per-rank counters sum, so slo(union) == slo(sums) —
    # the property that makes the shard-level fold honest
    a, b = row(ok=50), row(ok=49, shed=1)
    combined = obs.serve_slo({"0": a, "1": b})
    assert combined == obs.serve_slo({"0": row(ok=99, shed=1)})
    assert combined["requests"] == 100 and combined["bad"] == 1


# ------------------------------------------------------ shard-level fold
def test_status_fold_keeps_trace_and_slo_sections():
    """The new per-job sections ride the job row through
    merge_status_docs: jobs are disjoint across shards, so the
    hierarchical fold equals the flat fold with both sections intact."""
    def doc(shard, name, trace_records):
        return {"ts": 10.0 + shard, "shard": shard,
                "service": {"jobs_active": [name],
                            "counters": {"job.created": 1}},
                "jobs": {name: {
                    "world": 2, "done": False,
                    "trace": {"ops_assembled": 1,
                              "records": trace_records,
                              "bound_by": "link 0->1 (1/1 ops)",
                              "links": {"0->1": {"n": trace_records,
                                                 "mean_sec": 0.01,
                                                 "bytes": 1024}}},
                    "serve_slo": {"target": 0.99, "requests": 100,
                                  "bad": 1, "burn_rate": 1.0,
                                  "budget_remaining": 0.0}}}}

    d0, d1, d2 = doc(0, "ja", 3), doc(1, "jb", 5), doc(2, "jc", 7)
    flat = obs_export.merge_status_docs([d0, d1, d2])
    hier = obs_export.merge_status_docs(
        [obs_export.merge_status_docs([d0, d1]),
         obs_export.merge_status_docs([d2])])
    assert json.dumps(hier, sort_keys=True) == \
        json.dumps(flat, sort_keys=True)
    assert flat["jobs"]["jb"]["trace"]["records"] == 5
    assert flat["jobs"]["jc"]["serve_slo"]["burn_rate"] == 1.0
    assert flat["jobs"]["ja"]["shard"] == 0


def test_metrics_fold_trace_and_slo_series():
    """The new Prometheus series are all per-job labeled, so they pass
    through the page merge verbatim and the two-level fold equals the
    flat fold."""
    def page(name, burn, recs):
        return obs_export.prometheus_text(
            [("rabit_serve_slo_burn_rate", {"job": name}, burn),
             ("rabit_serve_slo_budget_remaining", {"job": name}, 0.5),
             ("rabit_trace_records_total", {"job": name}, recs),
             ("rabit_trace_link_seconds_mean",
              {"job": name, "link": "0->1"}, 0.01)],
            {"rabit_serve_slo_burn_rate": "gauge",
             "rabit_serve_slo_budget_remaining": "gauge",
             "rabit_trace_records_total": "counter",
             "rabit_trace_link_seconds_mean": "gauge"})

    p0, p1, p2 = page("ja", 0.5, 3), page("jb", 1.0, 5), page("jc", 0, 7)
    flat = obs_export.merge_prometheus_pages([p0, p1, p2])
    hier = obs_export.merge_prometheus_pages(
        [obs_export.merge_prometheus_pages([p0, p1]), p2])
    assert hier == flat
    assert 'rabit_serve_slo_burn_rate{job="jb"} 1' in flat
    assert 'rabit_trace_records_total{job="jc"} 7' in flat
    assert 'rabit_trace_link_seconds_mean{job="ja",link="0->1"} 0.01' \
        in flat


# ------------------------------------------- tracker ingest + exposition
def _get(port: int, path: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read().decode()


def _frame(rank, hops, ts=None, rtt=0.02, serve=None):
    payload = {"rank": rank, "counters": {"op.allreduce.count": 1},
               "gauges": {"hb.rtt.seconds.p50": rtt},
               "ts": time.time() if ts is None else ts, "hops": hops}
    if serve:
        payload["serve"] = serve
        payload["counters"].update(
            {f"serve.requests.{k}": v for k, v in serve.items()})
    return json.dumps(payload).encode()


def test_tracker_trace_route_metrics_and_status():
    """Streamed hop records land in the job's assembler; /trace serves
    the per-job reports and the Perfetto export; /metrics grows the
    trace + SLO series; /status grows the trace + serve_slo sections."""
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, obs_port=0)
    try:
        job = t._admit("tj", 2)
        job._obs_frame_ingest("0", _frame(
            0, [_hop(0, 0, 1, 100.0, 100.1)],
            serve={"ok": 99, "shed": 1}))
        job._obs_frame_ingest("1", _frame(
            1, [_hop(0, 1, 0, 100.1, 100.3)]))
        assert job._traces.records == 2
        # skew calibration folded an offset sample per frame
        assert job._traces._offsets

        trace_doc = json.loads(_get(t.obs_port, "/trace"))
        rep = trace_doc["jobs"]["tj"]
        assert rep["records"] == 2 and rep["bound_by"]
        chrome = json.loads(_get(t.obs_port, "/trace?job=tj"))
        assert chrome["job"] == "tj"
        assert any(e.get("ph") == "X" for e in chrome["traceEvents"])
        key = ",".join(map(str, rep["last_op"]["key"]))
        by_op = json.loads(_get(t.obs_port, f"/trace?job=tj&op={key}"))
        assert len([e for e in by_op["traceEvents"]
                    if e.get("ph") == "X"]) == 2
        missing = json.loads(_get(t.obs_port, "/trace?job=nope"))
        assert "error" in missing

        metrics = _get(t.obs_port, "/metrics")
        assert 'rabit_trace_records_total{job="tj"} 2' in metrics
        assert 'rabit_trace_ops_assembled_total{job="tj"} 1' in metrics
        assert 'link="1->0"' in metrics
        assert 'rabit_serve_slo_burn_rate{job="tj"} 1' in metrics
        assert 'rabit_serve_slo_budget_remaining{job="tj"} 0' in metrics

        status = json.loads(_get(t.obs_port, "/status"))
        sj = status["jobs"]["tj"]
        assert sj["trace"]["records"] == 2
        assert sj["serve_slo"]["bad"] == 1
    finally:
        t.stop()
        t._close_all()


def test_rabit_top_bound_by_and_json(capfd):
    """rabit_top renders the bound-by verdict (and the timeline under
    --trace); --once --json emits the raw /status document with the
    trace section intact."""
    from rabit_tpu.tools import rabit_top
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, obs_port=0)
    try:
        job = t._admit("tj", 2)
        job._obs_frame_ingest("0", _frame(
            0, [_hop(0, 0, 1, 100.0, 100.1)]))
        job._obs_frame_ingest("1", _frame(
            1, [_hop(0, 1, 0, 100.1, 100.3)]))
        assert rabit_top.main(["--port", str(t.obs_port), "--once",
                               "--trace"]) == 0
        out = capfd.readouterr().out
        assert "bound by: link 1->0" in out
        assert "hop1" in out  # the --trace timeline rendered
        assert rabit_top.main(["--port", str(t.obs_port), "--once",
                               "--json"]) == 0
        doc = json.loads(capfd.readouterr().out)
        assert doc["jobs"]["tj"]["trace"]["records"] == 2
    finally:
        t.stop()
        t._close_all()


# --------------------------------------------------- postmortem analysis
def _flight(rank, reason, *, peer=None, inflight=None, events=(),
            world=4):
    rec = {"rank": rank, "reason": reason, "ts": 100.0 + rank,
           "pid": 1000 + rank, "inflight": inflight,
           "events": list(events), "world": world}
    if peer is not None:
        rec["peer"] = peer
    return rec


def test_reconstruct_names_corpse_and_inflight_op():
    from rabit_tpu.tools.postmortem import reconstruct

    op = {"kind": "allreduce", "seq": 6, "epoch": 0, "version": 0,
          "nbytes": 4096}
    recs = [
        _flight(0, "link_error", peer=1, inflight=op,
                events=[{"ts": 100.0, "name": "link_error", "peer": 1}]),
        _flight(2, "link_error", peer=1, inflight=op,
                events=[{"ts": 100.1, "name": "link_error", "peer": 1}]),
        # a cascade victim blames a SURVIVOR — that vote must not count
        _flight(3, "link_error", peer=0, inflight=op,
                events=[{"ts": 100.2, "name": "link_error", "peer": 0}]),
    ]
    v = reconstruct(recs, [{"job": "j", "world": 4, "lost": [1],
                            "epoch": 0, "committed_version": 0,
                            "events": [{"ts": 99.0, "name": "start"}]}])
    assert v["first_dead"] == 1
    assert v["blame_votes"] == {"1": 2}
    assert v["op_in_flight"]["seq"] == 6
    assert v["op_in_flight"]["votes"] == 3
    assert v["survivors"] == [0, 2, 3]
    assert "1->0" not in (v["stalled_links"] or [])
    assert "0->1" in v["stalled_links"]
    # the merged timeline interleaves tracker + rank events by ts
    ts = [e["ts"] for e in v["last_events"]]
    assert ts == sorted(ts) and v["last_events"][0]["name"] == "start"


def test_reconstruct_degrades_without_blame_evidence():
    from rabit_tpu.tools.postmortem import reconstruct

    # no link_error evidence at all: fall back to the tracker's lost
    # list, then to the missing-rank inference
    v = reconstruct([_flight(0, "sigterm")], [{"world": 2, "lost": [1]}])
    assert v["first_dead"] == 1
    v = reconstruct([_flight(0, "sigterm"), _flight(1, "sigterm"),
                     _flight(2, "sigterm")], [])
    assert v.get("first_dead") == 3  # world 4, rank 3 never wrote
    assert "op_in_flight" not in v
    v = reconstruct([_flight(0, "abort", world=0)], [])
    assert "first_dead" not in v


def test_trace_report_analyze():
    from rabit_tpu.tools.trace_report import analyze

    rep = {"ops_assembled": 4, "records": 16,
           "bound_by": "link 1->0 (3/4 ops)",
           "links": {"0->1": {"n": 4, "mean_sec": 0.001, "bytes": 4096},
                     "1->0": {"n": 4, "mean_sec": 0.02, "bytes": 4096}},
           "last_op": {"key": [0, 0, 6, "allreduce"],
                       "critical": {"rank": 1, "link": "1->0", "hop": 1,
                                    "kind": "allreduce", "sec": 0.02}}}
    a = analyze(rep)
    assert a["bound_by"] == "link 1->0 (3/4 ops)"
    assert a["costliest_links"][0] == "1->0"  # ranked by total cost
    assert a["last_op"]["critical"]["link"] == "1->0"


def test_trace_report_loads_both_document_shapes():
    """_job_traces accepts a live /status scrape ({"jobs": {...}}) AND
    a flat teardown journal (tracker.<job>.json from --trace-dir) —
    the first thing an operator points the tool at after a run."""
    from rabit_tpu.tools.trace_report import _job_traces

    rep = {"ops_assembled": 1, "records": 4, "links": {}}
    status = {"jobs": {"j0": {"trace": rep}, "j1": {"world": 2}}}
    assert _job_traces(status) == {"j0": rep}
    journal = {"job": "j0", "world": 2, "events": [], "trace": rep}
    assert _job_traces(journal) == {"j0": rep}
    # a journal with no assembled traces yields nothing, not a crash
    assert _job_traces({"job": "j0", "world": 2}) == {}


# ------------------------------------------------- distributed gates
def _poll_trace(port: int, hits: dict, deadline_sec: float = 90.0) -> None:
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        try:
            doc = json.loads(_get(port, "/trace", timeout=2))
        except (OSError, ValueError):
            time.sleep(0.1)
            continue
        for name, rep in (doc.get("jobs") or {}).items():
            recs = (rep or {}).get("records", 0)
            if recs and (rep.get("last_op") or {}).get("records"):
                ranks = {d.get("rank")
                         for d in rep["last_op"]["records"]}
                if len(ranks) >= 2:
                    hits["report"] = rep
                    try:
                        hits["chrome"] = json.loads(
                            _get(port, f"/trace?job={name}", timeout=2))
                    except (OSError, ValueError):
                        pass
                    return
        time.sleep(0.1)


def test_trace_end_to_end_world2_scrape(tmp_path):
    """A world-2 pysocket job with every op traced: the mid-run /trace
    scrape returns an assembled cross-rank timeline, the Perfetto
    export validates, and /metrics carries the trace series."""
    from rabit_tpu.tracker.launch_local import launch
    from rabit_tpu.utils.net import free_port

    port = free_port("127.0.0.1")
    hits: dict = {}
    poller = threading.Thread(target=_poll_trace, args=(port, hits),
                              daemon=True)
    poller.start()
    code = launch(2, [sys.executable, VICTIM, "4096", "40"],
                  extra_env={"RABIT_ENGINE": "pysocket",
                             "RABIT_OBS": "1",
                             "RABIT_OBS_FLUSH_SEC": "0.2",
                             "RABIT_TRACE_SAMPLE": "1",
                             "RABIT_ITER_SLEEP": "0.05"},
                  obs_port=port, trace_dir=str(tmp_path / "trace"))
    assert code == 0
    poller.join(timeout=10)
    assert "report" in hits, "no cross-rank op ever assembled on /trace"
    rep = hits["report"]
    assert rep["records"] >= 2 and rep["links"]
    assert rep["last_op"]["critical"]["link"]
    # the export is a loadable Chrome-trace document
    chrome = hits.get("chrome") or {}
    slices = [e for e in chrome.get("traceEvents", [])
              if e.get("ph") == "X"]
    assert slices, "no trace slices in the Perfetto export"
    assert all({"name", "cat", "pid", "ts", "dur"} <= set(e)
               for e in slices)
    # a healthy job leaves no flight records behind
    assert obs.load_flight_records(str(tmp_path / "trace")) == []
    # ...but the tracker dumped its control-plane journal at teardown
    from rabit_tpu.tools.postmortem import load_tracker_journals
    journals = load_tracker_journals(str(tmp_path / "trace"))
    assert journals and journals[0].get("trace", {}).get("records", 0) > 0


def test_flight_persist_on_linkerror_and_sigterm(tmp_path):
    """A world-2 crash round covering both fault paths: the victim
    SIGTERMs itself (its handler persists reason="sigterm"), the
    survivor's wedged collective escalates to a LinkError whose fault
    path persists the in-flight op and the blamed peer.  The launcher's
    teardown SIGTERM races the survivor's own exit, so the survivor's
    LAST record may carry either reason — but the link_error evidence
    (the ring event and the armed op) survives both orders, which is
    exactly the property postmortem reconstruction leans on."""
    from rabit_tpu.tracker.launch_local import launch

    trace_dir = tmp_path / "trace"
    kill_iter = 3
    code = launch(2, [sys.executable, VICTIM, "2048", "8"],
                  extra_env={"RABIT_ENGINE": "pysocket",
                             "RABIT_PM_KILL_RANK": "1",
                             "RABIT_PM_KILL_ITER": str(kill_iter),
                             "RABIT_PM_SIGNAL": "TERM",
                             "RABIT_TIMEOUT_SEC": "5"},
                  trace_dir=str(trace_dir))
    assert code != 0  # the job is supposed to die
    recs = {r["rank"]: r
            for r in obs.load_flight_records(str(trace_dir))}
    assert recs[1]["reason"] == "sigterm"
    surv = recs[0]
    assert surv["reason"] in ("link_error", "sigterm")
    if surv["reason"] == "link_error":
        # The wedged collective escalated first: the fault path blamed
        # the dead peer and the ring holds the link_error event.  (When
        # the teardown SIGTERM wins the race instead, the record's
        # reason is "sigterm" and no wire error ever fired — the
        # in-flight op below is the evidence that survives both orders.)
        assert surv["peer"] == 1
        assert any(e["name"] == "link_error" and e.get("peer") == 1
                   for e in surv["events"])
    op = surv["inflight"]
    assert op["kind"] == "allreduce" and op["seq"] == kill_iter
    # flight recording is independent of rabit_obs (always on)
    assert "RABIT_OBS" not in os.environ


# --------------------------------------------------------- the soak gate
@pytest.mark.slow
def test_postmortem_soak_gate():
    """The headline crash-forensics gate: a world-4 job with a seeded
    rank SIGKILLed mid-collective; tools/postmortem.py must name the
    first-dead rank and the in-flight op (kind/seq) from the persisted
    flight records + tracker journal alone (see tools/soak.py
    --postmortem for the assertions)."""
    from rabit_tpu.tools import soak

    assert soak.main(["--postmortem", "--rounds", "2",
                      "--seed", "11"]) == 0
