"""Serialization layer tests (streams, Serializable, base64)."""
import io

import numpy as np

from rabit_tpu.utils import (
    Base64InStream,
    Base64OutStream,
    MemoryBufferStream,
    MemoryFixSizeBuffer,
    PickleSerializable,
    Serializable,
    Stream,
)
from rabit_tpu.utils.serial import deserialize_model, serialize_model


def test_memory_buffer_stream_roundtrip():
    s = MemoryBufferStream()
    s.write_u64(42)
    s.write_bytes(b"hello")
    s.write_str("world")
    s.seek(0)
    assert s.read_u64() == 42
    assert s.read_bytes() == b"hello"
    assert s.read_str() == "world"


def test_fix_size_buffer_inplace():
    buf = bytearray(16)
    s = MemoryFixSizeBuffer(buf)
    s.write(b"\x01\x02\x03")
    assert buf[:3] == b"\x01\x02\x03"
    s.seek(0)
    assert s.read(3) == b"\x01\x02\x03"


def test_custom_serializable():
    class Model(Serializable):
        def __init__(self, w=None):
            self.w = w

        def save(self, stream: Stream):
            stream.write_bytes(np.asarray(self.w, dtype=np.float32).tobytes())

        def load(self, stream: Stream):
            self.w = np.frombuffer(stream.read_bytes(), dtype=np.float32).copy()

    m = Model([1.0, 2.0, 3.0])
    blob = m.to_bytes()
    m2 = Model()
    m2.from_bytes(blob)
    np.testing.assert_array_equal(m2.w, [1.0, 2.0, 3.0])

    # serialize_model dispatches on Serializable (1-byte format tag + body)
    blob2 = serialize_model(m)
    assert blob2 == b"S" + blob
    m3 = deserialize_model(blob2, into=Model())
    np.testing.assert_array_equal(m3.w, [1.0, 2.0, 3.0])


def test_pickle_serializable():
    p = PickleSerializable({"a": 1})
    blob = p.to_bytes()
    q = PickleSerializable()
    q.from_bytes(blob)
    assert q.obj == {"a": 1}


def test_base64_streams():
    sink = io.BytesIO()
    out = Base64OutStream(sink)
    out.write(b"\x00\xffbinary model\x01")
    out.finish()
    encoded = sink.getvalue()
    assert b"\x00" not in encoded  # text-safe

    src = io.BytesIO(encoded)
    instream = Base64InStream(src)
    assert instream.read(100) == b"\x00\xffbinary model\x01"
