"""Tracker topology unit tests + multiprocess integration of the base engine."""
import sys

import pytest

from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.tracker import ring_neighbors, tree_neighbors


def test_tree_topology():
    # world of 7: full binary tree
    parent, nb = tree_neighbors(0, 7)
    assert parent == P.NONE and nb == [1, 2]
    parent, nb = tree_neighbors(1, 7)
    assert parent == 0 and nb == [0, 3, 4]
    parent, nb = tree_neighbors(6, 7)
    assert parent == 2 and nb == [2]


def test_tree_covers_world():
    for world in (1, 2, 3, 5, 8, 16, 33):
        seen = set()
        for r in range(world):
            parent, nb = tree_neighbors(r, world)
            if r == 0:
                assert parent == P.NONE
            else:
                assert 0 <= parent < r
                assert parent in nb
            seen.add(r)
        assert seen == set(range(world))


def test_ring_neighbors():
    assert ring_neighbors(0, 4) == (3, 1)
    assert ring_neighbors(3, 4) == (2, 0)
    assert ring_neighbors(0, 1) == (0, 0)


def test_rank_assignment_shuffled_but_stable():
    """New task_ids draw from a SHUFFLED free-rank pool (the reference
    shuffles todo_nodes for load balance, rabit_tracker.py:242); a
    re-registering task_id keeps its old rank (stable-rank contract)."""
    from types import SimpleNamespace

    from rabit_tpu.tracker.tracker import Tracker

    tr = Tracker(8)
    try:
        tr._pending = [SimpleNamespace(task_id=str(i)) for i in range(8)]
        tr._assign_ranks()
        assert sorted(tr._rank_of.values()) == list(range(8))
        before = dict(tr._rank_of)
        # re-registration (restart) of two tasks plus no new ones:
        # ranks must not move
        tr._pending = [SimpleNamespace(task_id="3"),
                       SimpleNamespace(task_id="5")]
        tr._assign_ranks()
        assert tr._rank_of == before
    finally:
        tr.stop()


def test_relaunch_flag_semantics():
    """The tracker flags only start re-registrations of task_ids that
    already received a topology reply — a first-round worker and a
    recover-round survivor are never flagged (the XLA engine keys its
    degraded-rejoin path on this)."""
    import socket
    import threading

    from rabit_tpu.tracker.tracker import Tracker

    tr = Tracker(2)
    tr.start()

    def register(task_id: str, cmd: str) -> P.TopologyReply:
        sock = socket.create_connection((tr.host, tr.port), timeout=30)
        P.send_u32(sock, P.MAGIC)
        P.send_str(sock, cmd)
        P.send_str(sock, task_id)
        P.send_u32(sock, 2)
        P.send_str(sock, "127.0.0.1")
        P.send_u32(sock, 12345)
        reply = P.TopologyReply.recv(sock)
        sock.close()
        return reply

    def round_of(cmds: dict[str, str]) -> dict[str, P.TopologyReply]:
        out: dict[str, P.TopologyReply] = {}
        errors: list[BaseException] = []

        def run(t: str, c: str) -> None:
            try:
                out[t] = register(t, c)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [threading.Thread(target=run, args=(t, c))
                   for t, c in cmds.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        if errors:
            raise errors[0]
        return out

    try:
        # Round 1 (fresh start): nobody is a relaunch.
        r1 = round_of({"0": P.CMD_START, "1": P.CMD_START})
        assert {t: r.relaunched for t, r in r1.items()} == {"0": 0, "1": 0}
        # Round 2 (task 1 restarted mid-job; task 0 is a recovering
        # survivor): only the start re-registration is flagged.
        r2 = round_of({"0": P.CMD_RECOVER, "1": P.CMD_START})
        assert r2["0"].relaunched == 0
        assert r2["1"].relaunched == 1
        # Ranks stay stable across the rounds (task_id -> rank map).
        assert {t: r.rank for t, r in r1.items()} == \
               {t: r.rank for t, r in r2.items()}
    finally:
        tr.stop()


@pytest.mark.parametrize("engine", ["pysocket", "native"])
@pytest.mark.parametrize("world", [2, 3, 4, 7])
def test_multiprocess_collectives(world, engine, request):
    """N real worker processes through the tracker, per engine."""
    from rabit_tpu.tracker.launch_local import launch

    if engine == "native":
        # Only the native runs need the C++ build; pysocket coverage must
        # never be skipped by a broken toolchain.
        request.getfixturevalue("native_lib")
    code = launch(world, [sys.executable, "tests/workers/check_basic.py", "500"],
                  extra_env={"RABIT_ENGINE": engine})
    assert code == 0


@pytest.mark.parametrize("engine", ["pysocket", "native"])
def test_multiprocess_large_ring(engine, request):
    from rabit_tpu.tracker.launch_local import launch

    if engine == "native":
        request.getfixturevalue("native_lib")
    code = launch(4, [sys.executable, "tests/workers/check_basic.py", "100000"],
                  extra_env={"RABIT_ENGINE": engine})
    assert code == 0


def test_mixed_engine_interop(native_lib):
    """C++ and Python engines share the wire protocol: mixed job works."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(5, [sys.executable, "tests/workers/check_mixed.py"])
    assert code == 0


def test_rendezvous_storm_tool():
    """The storm harness (doc/scaling.md's W=1024 barrier measurement)
    runs real registrations + links + a cmd=recover re-round: keep the
    tool working so the recorded numbers stay reproducible."""
    sys.path.insert(0, "tools")
    try:
        import rendezvous_storm
        t_start, t_recover = rendezvous_storm.storm(16)
    finally:
        sys.path.remove("tools")
    assert t_start > 0 and t_recover > 0


def test_pin_ranks_assignment(monkeypatch):
    """RABIT_TRACKER_PIN_RANKS=1: decimal task_ids in range claim their
    own rank; out-of-range, non-decimal, and already-known ids fall back
    to the free pool; restarted ids keep their old rank regardless."""
    from rabit_tpu.tracker.tracker import Tracker, _Registrant

    def regs(*tids):
        return [_Registrant(None, t, "h", 0) for t in tids]

    monkeypatch.setenv("RABIT_TRACKER_PIN_RANKS", "1")
    monkeypatch.setenv("RABIT_TRACKER_SHUFFLE", "0")
    tr = Tracker.__new__(Tracker)          # no sockets needed
    tr.n_workers = 4
    tr._rank_of = {}
    tr._pending = regs("2", "0", "zebra", "9")   # 9 out of range
    tr._assign_ranks()
    assert tr._rank_of["2"] == 2 and tr._rank_of["0"] == 0
    # non-claimants fill remaining ranks {1, 3} in arrival order
    assert tr._rank_of["zebra"] == 1 and tr._rank_of["9"] == 3

    # stable-rank contract beats pinning: a restarted "zebra" keeps 1,
    # and a fresh "1" cannot claim the taken rank
    tr2 = Tracker.__new__(Tracker)
    tr2.n_workers = 3
    tr2._rank_of = {"zebra": 1}
    tr2._pending = regs("1", "zebra", "0")
    tr2._assign_ranks()
    assert tr2._rank_of["zebra"] == 1
    assert tr2._rank_of["0"] == 0
    assert tr2._rank_of["1"] == 2          # rank 1 taken -> free pool

    # pinning off (default): integer ids get arrival order like any id
    monkeypatch.delenv("RABIT_TRACKER_PIN_RANKS")
    tr3 = Tracker.__new__(Tracker)
    tr3.n_workers = 2
    tr3._rank_of = {}
    tr3._pending = regs("1", "0")
    tr3._assign_ranks()
    assert tr3._rank_of == {"1": 0, "0": 1}


# --------------------------------------------------- heartbeat detector
def _hb_hello(addr, task_id, cmd, period_ms=None, world=2):
    """Open one tracker command connection (heartbeat channels stay
    open; the caller owns the socket)."""
    import socket

    s = socket.create_connection(addr)
    P.send_u32(s, P.MAGIC)
    P.send_str(s, cmd)
    P.send_str(s, task_id)
    P.send_u32(s, world)
    if period_ms is not None:
        P.send_u32(s, period_ms)
    return s


def test_heartbeat_deadline_marks_dead_and_evicts_registrant():
    """A worker whose beats stop (socket still OPEN — the SIGSTOP shape
    the EOF-based registrant sweep cannot see) must be declared dead
    within the miss budget: its parked rendezvous registrant is evicted
    so the round re-opens, on_dead fires for the supervisor, and the
    liveness transition lands in the tracker event timeline."""
    import time

    from rabit_tpu.tracker.tracker import Tracker

    dead = []
    t = Tracker(2, heartbeat_miss=2.0, on_dead=dead.append)
    t.start()
    reg = hb = None
    try:
        addr = (t.host, t.port)
        reg = _hb_hello(addr, "0", P.CMD_START)
        P.send_str(reg, "127.0.0.1")
        P.send_u32(reg, 23456)  # parked: world 2, one registrant
        hb = _hb_hello(addr, "0", P.CMD_HEARTBEAT, period_ms=100)
        for i in range(3):
            P.send_u32(hb, i + 1)
            time.sleep(0.05)
        deadline = time.monotonic() + 5
        while not dead and time.monotonic() < deadline:
            time.sleep(0.05)
        assert dead and dead[0] == "0", dead
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            with t._pending_lock:
                if not t._pending:
                    break
            time.sleep(0.05)
        with t._pending_lock:
            assert not t._pending  # corpse evicted, round re-opened
        phases = [e["phase"] for e in t._events]
        assert "alive" in phases and "dead" in phases, phases
    finally:
        t.stop()
        for s in (reg, hb):
            if s is not None:
                s.close()


def test_heartbeat_bye_and_relaunch_transitions():
    """A clean HEARTBEAT_BYE never produces a dead verdict; a SECOND
    heartbeat channel for the same task is recorded as its relaunched
    life (the restart event the obs timeline renders)."""
    import time

    from rabit_tpu.tracker.tracker import Tracker

    dead = []
    t = Tracker(2, heartbeat_miss=2.0, on_dead=dead.append)
    t.start()
    try:
        addr = (t.host, t.port)
        hb = _hb_hello(addr, "1", P.CMD_HEARTBEAT, period_ms=50)
        P.send_u32(hb, 1)
        P.send_u32(hb, P.HEARTBEAT_BYE)
        hb.close()
        time.sleep(0.5)  # several miss budgets: bye must have parked it
        assert dead == [], dead
        hb2 = _hb_hello(addr, "1", P.CMD_HEARTBEAT, period_ms=50)
        P.send_u32(hb2, 1)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            evs = [(e["phase"], e.get("relaunched")) for e in t._events
                   if e.get("task") == "1"]
            if ("alive", 1) in evs:
                break
            time.sleep(0.05)
        evs = [(e["phase"], e.get("relaunched")) for e in t._events
               if e.get("task") == "1"]
        assert ("alive", None) in evs or ("alive", 1) in evs, evs
        assert ("shutdown", None) in evs, evs
        assert ("alive", 1) in evs, evs  # second channel == relaunch
        # Clean goodbye: an abrupt close here would have the (live)
        # monitor thread log a legitimate 'lost (EOF)' asynchronously,
        # past this test's output capture.
        P.send_u32(hb2, P.HEARTBEAT_BYE)
        hb2.close()
    finally:
        t.stop()
