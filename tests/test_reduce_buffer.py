"""rabit_reduce_buffer: bounded, chunked collectives.

The reference chunks every allreduce through a bounded reduce buffer
(default 256 MB) so per-op scratch memory is configuration-bounded
(reference: src/allreduce_base.cc:31,117-132,326-491).  These tests run
multi-worker jobs whose payloads are 32x the configured budget and
assert both numeric correctness and the engine-reported scratch peak.
"""
import sys

import pytest

from rabit_tpu.utils.units import parse_byte_size


def test_parse_byte_size():
    assert parse_byte_size("256MB") == 256 << 20
    assert parse_byte_size("64KB") == 64 << 10
    assert parse_byte_size("1gb") == 1 << 30
    assert parse_byte_size("2 MB") == 2 << 20
    assert parse_byte_size("1048576") == 1 << 20
    assert parse_byte_size(4096) == 4096
    assert parse_byte_size("0.5MB") == 512 << 10
    with pytest.raises(ValueError):
        parse_byte_size("12XB")
    with pytest.raises(ValueError):
        parse_byte_size("MB")
    with pytest.raises(ValueError):
        parse_byte_size("0")
    # non-finite / scientific-notation garbage must be rejected, not
    # silently converted (the C++ twin rejects inf/nan/overflow too)
    for bad in ("inf", "nan", "1e30GB", "-4KB"):
        with pytest.raises(ValueError):
            parse_byte_size(bad)


def test_parse_byte_size_native(native_lib):
    """The C++ twin (BaseEngine::ParseByteSize) agrees with the Python
    parser — exercised end-to-end through the native jobs below; here we
    only check the error path surfaces cleanly."""
    import rabit_tpu

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    with pytest.raises(Exception):
        rabit_tpu.init(rabit_engine="native", rabit_tracker_uri="127.0.0.1",
                       rabit_tracker_port="1", rabit_reduce_buffer="12XB")


def _run(engine: str, world: int, budget: str | None = "256KB") -> int:
    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_ENGINE": engine}
    if budget is None:  # per-worker budgets chosen inside the worker
        env["RABIT_MIXED_BUDGETS"] = "1"
    else:
        env["RABIT_REDUCE_BUFFER"] = budget
    return launch(world, [sys.executable,
                          "tests/workers/check_reduce_buffer.py"],
                  extra_env=env)


@pytest.mark.parametrize("world", [2, 4])
def test_bounded_scratch_pysocket(world):
    assert _run("pysocket", world) == 0


@pytest.mark.parametrize("world", [2, 4])
def test_bounded_scratch_native(world, native_lib):
    assert _run("native", world) == 0


@pytest.mark.parametrize("engine", ["pysocket", "native"])
def test_mixed_budgets_interoperate(engine, request):
    """Chunk sizes are a per-worker streaming detail, not a protocol
    parameter: workers with budgets from 64KB to 256MB in one job must
    agree bit-for-bit (per-link byte streams are identical regardless
    of chunking)."""
    if engine == "native":
        request.getfixturevalue("native_lib")
    assert _run(engine, 4, budget=None) == 0
