"""Self-healing control plane tests.

Covers the ISSUE 19 contract (doc/fault_tolerance.md "Replicated
directory & job migration"):

* the membership journal round-trips and its fold is idempotent — a
  duplicated suffix (follower re-sync after a leadership change) and a
  torn tail write both fold to the same state;
* generation monotonicity as a PROPERTY: over seeded recorded
  membership-event sequences mixing register/remove/takeover with
  crash-restarts (journal replayed into a fresh authority), the
  generation never decrements and a takeover never reuses one — the
  fencing argument every consumer's monotonic-adopt rule rests on;
* the deterministic lease: replica 0 leads from birth, replica i leads
  after exactly ``lease_miss`` consecutive missed probes of EVERY
  lower id, and leadership steps back the instant a lower id answers;
* a live 3-replica fleet survives leader death: the successor fences
  (strictly higher generation), journals the takeover, keeps serving
  registrations, and the postmortem names the dead replica from the
  membership journals alone;
* the client rides the replica set: rotation past a dead endpoint and
  the typed ``not_leader`` write redirect both land on the leader;
* the stale-cache degradation path logs ONE obs-visible warning per
  outage episode while every ridden refresh failure stays counted
  (the rate-limit regression test — pins ``stale_warnings``);
* chaos teeth at the directory link sites (``dir_register`` /
  ``dir_poll``) with deterministic injected↔detected pairing against
  the shard's retry/failure counters;
* live job migration end to end between two in-process shards:
  journal shipped at a commit boundary, destination replays and
  counts ``migrated_in`` (a transfer, NOT a restore), tombstone on
  the source steers registrations (typed ``REJECT_SHARD_MOVED``),
  epoch polls (forced bump) and goodbyes (forwarded, books close at
  the destination);
* every ``_accept_migration`` fence refuses typed and stateless.
"""
import json
import random
import socket
import time
import urllib.request

import pytest

from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.directory import (Directory, DirectoryClient,
                                         DirectoryServer, HashRing)
from rabit_tpu.tracker.replica import (EV_REGISTER, EV_REMOVE, LeaseState,
                                       MembershipJournal, fold_events)
from rabit_tpu.tracker.shard import ShardServer
from rabit_tpu.tools import postmortem

pytestmark = pytest.mark.shard


# ------------------------------------------------------------- helpers
def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _wait(pred, deadline_sec=10.0):
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _hello(addr, cmd, task_id, job=P.DEFAULT_JOB, world=0):
    s = socket.create_connection(addr, timeout=30)
    P.send_hello(s, cmd, task_id, world, job=job)
    return s


def _register(addr, task_id, cmd=P.CMD_START, job=P.DEFAULT_JOB,
              world=0, port=12345):
    s = _hello(addr, cmd, task_id, job=job, world=world)
    P.send_str(s, "127.0.0.1")
    P.send_u32(s, port)
    return s


# ------------------------------------------- the membership journal
def test_membership_journal_roundtrip_and_idempotent_fold(tmp_path):
    """The journal replays to the exact membership it recorded; a
    duplicated suffix (what a follower's cursor reset re-appends) and
    a torn tail write both fold to the same state."""
    path = tmp_path / "directory.r0.journal.jsonl"
    j = MembershipJournal(str(path))
    j.append({"ev": EV_REGISTER, "gen": 1, "index": 0,
              "host": "127.0.0.1", "port": 7000, "obs_port": 0})
    j.append({"ev": EV_REGISTER, "gen": 2, "index": 1,
              "host": "127.0.0.1", "port": 7001, "obs_port": 9001})
    j.append({"ev": EV_REMOVE, "gen": 3, "index": 0})
    gen, shards = j.replay()
    assert gen == 3 and sorted(shards) == [1]
    assert shards[1]["port"] == 7001 and shards[1]["obs_port"] == 9001

    # reopen == replica restart: same fold, sequence preserved
    j2 = MembershipJournal(str(path))
    assert j2.seq == j.seq
    assert j2.replay() == (gen, shards)

    # idempotence: replaying a duplicated suffix changes nothing —
    # what makes a follower-sync cursor reset safe
    evs = j2.events()
    assert fold_events(evs + evs[-2:]) == (gen, shards)

    # a torn tail write is skipped, the prefix still folds
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"ev": "register", "gen":')
    assert MembershipJournal(str(path)).replay() == (gen, shards)


def test_generation_monotonicity_property(tmp_path):
    """Over seeded recorded membership-event sequences — registers,
    removes, fenced takeovers, and crash-restarts that replay the
    journal into a fresh authority — the generation never decrements
    and a takeover never hands out a generation anyone has seen
    before.  This is the property every consumer's monotonic-adopt
    rule (and the stale-leader fence) rests on."""
    for trial in range(6):
        rng = random.Random(100 + trial)
        path = tmp_path / f"trial{trial}.jsonl"
        d = Directory(journal=MembershipJournal(str(path)))
        takeover_gens = set()
        prev_gen = 0
        for _ in range(80):
            op = rng.randrange(10)
            if op < 4:
                d.register(rng.randrange(5), "127.0.0.1",
                           7000 + rng.randrange(5), 0)
            elif op < 6:
                d.remove(rng.randrange(5))
            elif op < 8:
                # failover: the successor fences past both its own
                # journal and the highest generation it ever observed
                observed = d.generation + rng.randrange(3)
                g = d.takeover(rng.randrange(3), [rng.randrange(3)],
                               observed)
                assert g > prev_gen, "takeover decremented"
                assert g not in takeover_gens, "takeover gen reused"
                takeover_gens.add(g)
            else:
                # crash-restart: fold the recorded journal into a
                # fresh authority (the leader-bootstrap path)
                j = MembershipJournal(str(path))
                d = Directory(journal=j)
                d.install(*j.replay())
            assert d.generation >= prev_gen, "generation went backward"
            prev_gen = d.generation
        # the recorded event sequence itself is strictly increasing —
        # no reuse, no decrement, across every restart boundary
        gens = [ev["gen"] for ev in MembershipJournal(str(path)).events()]
        assert all(b > a for a, b in zip(gens, gens[1:])), gens


# ------------------------------------------------- the leader lease
def test_lease_election_and_stepdown():
    """Replica 0 leads from birth; replica i takes the lease after
    exactly ``lease_miss`` consecutive missed probes of every lower
    id, and hands it back the instant a lower id answers again."""
    assert LeaseState(0, 3).is_leader()  # vacuously: no lower ids

    l1 = LeaseState(1, 3)
    assert not l1.is_leader()
    l1.probe_result(0, False)
    l1.probe_result(0, False)
    assert not l1.is_leader()  # budget not yet spent
    l1.probe_result(0, False)
    assert l1.is_leader()
    assert l1.dead_lower() == [0] and l1.healthy_lower() == []

    # the deposed leader wakes: step down at once, adopt its gen
    l1.probe_result(0, True, generation=7)
    assert not l1.is_leader()
    assert l1.observed_gen == 7 and l1.healthy_lower() == [0]

    # replica 2 needs EVERY lower id to miss its full budget
    l2 = LeaseState(2, 2)
    l2.probe_result(0, False)
    l2.probe_result(0, False)
    assert not l2.is_leader()  # replica 1 still presumed healthy
    l2.probe_result(1, False)
    l2.probe_result(1, False)
    assert l2.is_leader()

    # an unknown (higher/self) peer id is ignored, not crashed on
    l2.probe_result(5, True, generation=99)
    assert l2.is_leader() and l2.observed_gen == 0


# ------------------------------------------- the replicated fleet
def test_replica_takeover_serves_writes_and_names_the_corpse(tmp_path):
    """3 in-process replicas: the client's write lands on the leader
    (following the typed ``not_leader`` redirect from a follower),
    leader death moves the lease to replica 1 within its miss budget,
    the takeover is FENCED (strictly higher generation) and journaled,
    and the postmortem names the dead replica from the membership
    journals alone."""
    ports = _free_ports(3)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        d = Directory(journal=MembershipJournal(
            str(tmp_path / f"directory.r{i}.journal.jsonl")))
        servers.append(DirectoryServer(
            d, port=p, replica_index=i, peers=urls,
            lease_sec=0.1, lease_miss=3).start())
    try:
        # a follower answers the write with the typed redirect; the
        # client follows it to the leader in the same call
        dc_follower = DirectoryClient(urls[1])
        snap = dc_follower.register(0, "127.0.0.1", 7000, 0)
        g0 = snap["generation"]
        assert g0 >= 1
        assert [s["index"] for s in snap["shards"]] == [0]

        # followers mirror the journal and serve read-only snapshots
        def follower_gen():
            with urllib.request.urlopen(urls[2] + "/directory",
                                        timeout=5) as resp:
                return json.loads(resp.read().decode())["generation"]
        assert _wait(lambda: follower_gen() >= g0, 10), \
            "follower never synced the leader's journal"

        servers[0].stop()  # the leader dies mid-flight
        assert _wait(lambda: servers[1].is_leader(), 15), \
            "replica 1 never took the lease"

        # the successor serves writes at a STRICTLY higher generation
        # (the fence), reached through the full replica list
        dc = DirectoryClient(",".join(urls))
        snap = dc.register(1, "127.0.0.1", 7001, 0)
        assert snap["generation"] > g0
        assert sorted(s["index"] for s in snap["shards"]) == [0, 1]

        # the takeover is journaled and the postmortem names the corpse
        dj = postmortem.load_directory_journals(str(tmp_path))
        verdict = postmortem.reconstruct([], [], dir_journals=dj)
        assert verdict.get("dead_replicas") == [0]
        assert any(t["by_replica"] == 1 and t["gen"] > g0
                   for t in verdict["directory_takeovers"])
    finally:
        for srv in servers[1:]:
            srv.stop()


def test_client_rotates_past_a_dead_endpoint():
    """A client given a replica list where the first endpoint is dead
    transparently rotates to a live one — no caller-visible error."""
    dead = _free_ports(1)[0]
    d = Directory()
    d.register(0, "127.0.0.1", 7000, 0)
    srv = DirectoryServer(d).start()
    try:
        dc = DirectoryClient(
            f"http://127.0.0.1:{dead},http://127.0.0.1:{srv.port}")
        snap = dc.refresh()
        assert snap["generation"] == d.generation
    finally:
        srv.stop()


def test_stale_snapshot_warns_once_per_outage_episode():
    """The degradation-path rate limit (ISSUE 19 satellite): during a
    directory outage every lookup rides the cached snapshot and is
    COUNTED, but only the episode's first ride logs — and a recovery
    re-arms the warning for the next outage."""
    d = Directory()
    d.register(0, "127.0.0.1", 7000, 0)
    srv = DirectoryServer(d).start()
    port = srv.port
    dc = DirectoryClient(f"http://127.0.0.1:{port}", max_age_sec=0.01)
    dc.refresh()

    srv.stop()  # outage #1
    for _ in range(6):
        time.sleep(0.02)  # age past max_age so every call re-refreshes
        snap = dc.snapshot()
        assert snap["generation"] == d.generation  # rides the cache
    assert dc.stale_rides >= 6
    assert dc.stale_warnings == 1  # one warning, not one per tick

    # recovery on the SAME port closes the episode...
    srv2 = DirectoryServer(d, port=port).start()
    try:
        time.sleep(0.02)
        assert dc.snapshot()["generation"] == d.generation
        assert dc.stale_warnings == 1
    finally:
        srv2.stop()

    # ...so outage #2 warns exactly once more
    for _ in range(4):
        time.sleep(0.02)
        dc.snapshot()
    assert dc.stale_warnings == 2
    assert dc.stale_rides >= 10


# ---------------------------------------- chaos at the dir_* sites
def test_chaos_dir_sites_pair_injected_with_detected(monkeypatch):
    """Deterministic injected↔detected pairing at the directory link
    sites: every ``dir_register`` reset surfaces as a counted
    registration retry, every ``dir_poll`` reset as a counted poll
    failure — and the plan's injected total matches exactly."""
    monkeypatch.setenv(
        "RABIT_CHAOS",
        "5:reset@dir_register=1.0*2;reset@dir_poll=1.0*3")
    d = Directory()
    srv = DirectoryServer(d).start()
    sh = None
    try:
        sh = ShardServer(1, shard_index=0,
                         directory=f"http://127.0.0.1:{srv.port}",
                         poll_sec=0.05)
        sh.start()
        plan = sh._dir._chaos
        assert plan is not None, "chaos plan never attached"
        # both register resets were ridden on the retry budget...
        assert sh._svc_counters["shard.register_retries"] == 2
        # ...and the poll-side rule drains against the failure counter
        assert _wait(lambda: sh._svc_counters.get(
            "shard.poll_failures", 0) >= 3, 15)
        assert _wait(lambda: plan.injected == 5, 5)
        assert sh._svc_counters["shard.poll_failures"] == 3
        # the fleet converged despite the faults
        assert sh._gen == d.generation
    finally:
        if sh is not None:
            sh.stop()
        srv.stop()


# ------------------------------------------------- live migration
def _name_owned_by(idx, members, prefix="mig"):
    ring = HashRing(members)
    for i in range(500):
        name = f"{prefix}{i}"
        if ring.owner(name) == idx:
            return name
    raise AssertionError(f"no name hashes to shard {idx} of {members}")


def test_live_migration_end_to_end_with_tombstone_steering(tmp_path):
    """The full handoff between two live shards: the scale-up join
    does NOT cold-adopt the running job (it is live on its sticky
    owner), the drain ships it at a commit boundary, the destination
    counts ``migrated_in`` as a transfer (never a restore), and the
    source's tombstone steers every class of late traffic —
    registration (typed redirect naming the new owner), epoch poll
    (forced bump to the destination's rescale round), goodbye
    (forwarded so the books close at the destination)."""
    d = Directory()
    # a name that shard 0 owns alone but shard 1 owns once it joins
    name = _name_owned_by(1, [0, 1])
    a = ShardServer(1, shard_index=0, directory=d,
                    state_dir=str(tmp_path), poll_sec=0.05,
                    migrate_after_sec=0.2, migrate_max=2, obs_port=0)
    a.start()
    b = None
    try:
        s = _register((a.host, a.port), "w0", job=name, world=1)
        topo = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(topo, P.TopologyReply) and topo.world == 1

        b = ShardServer(1, shard_index=1, directory=d,
                        state_dir=str(tmp_path), poll_sec=0.05,
                        obs_port=0)
        b.start()
        # the join must NOT have cold-adopted the journal of a job
        # that is live on its sticky previous owner
        with b._jobs_lock:
            assert name not in b._jobs

        assert _wait(lambda: b._svc_counters.get(
            "job.migrated_in", 0) == 1, 15), "migration never committed"
        assert a._svc_counters["job.migrated_out"] == 1
        with b._jobs_lock:
            assert name in b._jobs
        with a._jobs_lock:
            assert name not in a._jobs
        tomb = a._tombstones[name]
        assert tomb["shard"] == 1
        assert (tomb["host"], tomb["port"]) == (b.host, b.port)
        # a transfer, not an admission: no restore entered the books
        assert b._svc_counters.get("job.restored", 0) == 0

        # late registration at the source: typed redirect to the owner
        s = _register((a.host, a.port), "w0", job=name, world=1)
        reply = P.TopologyReply.recv_or_reject(s)
        s.close()
        assert isinstance(reply, P.RejectReply)
        assert reply.code == P.REJECT_SHARD_MOVED
        gen, owner, host, port = P.parse_shard_moved(reply.reason)
        assert owner == 1 and (host, port) == (b.host, b.port)
        assert gen == d.generation
        assert a._svc_counters["shard.tombstone_redirects"] >= 1

        # late epoch poll at the source: forced bump to the promised
        # rescale round — the worker's commit boundary re-registers
        s = _hello((a.host, a.port), P.CMD_EPOCH, "w0", job=name)
        P.send_u32(s, 0)  # committed version
        cur, nxt, world = (P.recv_u32(s), P.recv_u32(s), P.recv_u32(s))
        s.close()
        assert cur == tomb["epoch"] and nxt == tomb["epoch"] + 1
        assert world == 1
        assert a._svc_counters["shard.tombstone_epoch_bumps"] >= 1

        # late goodbye at the source: forwarded, books close at B
        _hello((a.host, a.port), P.CMD_SHUTDOWN, "w0", job=name).close()
        assert _wait(lambda: a._svc_counters.get(
            "shard.goodbyes_forwarded", 0) >= 1, 10)
        with b._jobs_lock:
            job = b._jobs[name]
        assert _wait(lambda: job.done, 10), "goodbye never landed at B"
    finally:
        if b is not None:
            b.stop()
        a.stop()


def test_accept_migration_fences_are_typed_and_stateless(tmp_path):
    """Every ``_accept_migration`` refusal is typed and leaves no job
    state behind — the source rolls back on each of them."""
    d = Directory()
    sh = ShardServer(1, shard_index=0, directory=d,
                     state_dir=str(tmp_path), poll_sec=0.05)
    sh.start()
    try:
        d.register(1, "127.0.0.1", _free_ports(1)[0], 0)  # phantom peer
        assert _wait(lambda: sh._gen == d.generation, 10)

        def offer(name, gen=None):
            return sh._accept_migration({
                "job": name, "src": 1, "world": 1, "epoch": 0,
                "generation": d.generation if gen is None else gen})

        assert offer("../evil")["reason"] == "bad_job"
        assert offer(P.DEFAULT_JOB)["reason"] == "bad_job"

        mine = _name_owned_by(0, [0, 1], prefix="fence")
        theirs = _name_owned_by(1, [0, 1], prefix="fence")
        assert offer(theirs)["reason"] == "not_owner"
        # a generation from the future the directory can't confirm
        assert offer(mine, gen=d.generation + 7)["reason"] == "stale_gen"

        sh._replay_gate.set()
        try:
            assert offer(mine)["reason"] == "replaying"
        finally:
            sh._replay_gate.clear()

        # ring-correct, current generation — but nothing to replay
        assert offer(mine)["reason"] == "no_journal"
        with sh._jobs_lock:
            assert mine not in sh._jobs and theirs not in sh._jobs
    finally:
        sh.stop()


# --------------------------------------------------- the slow gates
@pytest.mark.slow
def test_soak_self_healing_gate():
    """The ISSUE 19 acceptance gate: 3 directory replicas, leader
    SIGKILL mid-training, scale-up driving >=1 live migration — every
    job finishes bit-exact, the books balance, the postmortem names
    the dead replica."""
    from rabit_tpu.tools import soak
    rc = soak.main(["--shards", "3", "--tenants", "6", "--rounds", "1",
                    "--seed", "11", "--ndata", "2000", "--niter", "8",
                    "--dir-replicas", "3", "--dir-kill", "--migrate"])
    assert rc == 0


@pytest.mark.slow
def test_soak_self_healing_composes_with_chaos():
    """The same gate under the seeded chaos plan — injected resets and
    stalls at the directory sites ride the retry budgets without
    costing a job."""
    from rabit_tpu.tools import soak
    rc = soak.main(["--shards", "3", "--tenants", "6", "--rounds", "1",
                    "--seed", "7", "--ndata", "2000", "--niter", "8",
                    "--dir-replicas", "3", "--dir-kill", "--migrate",
                    "--chaos"])
    assert rc == 0
