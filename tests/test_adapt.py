"""Adaptive-controller tests (doc/performance.md "Online adaptation").

Fast, synthetic-fold coverage of the closed loop's decision machinery:
the pure :class:`ScheduleScorer` (min-sample gating, hysteresis — no
flapping on noisy costs — and decision determinism), the
:class:`AdaptiveController`'s probe lifecycle and straggler demotion
(threshold reuse of ``RABIT_STRAGGLER_FACTOR``), the SpanMerger's
per-(schedule, payload-bucket) cost fold, the demoted-aware
hierarchical leader election, the TuningCache's nearest-world fallback
+ online merge round trip, the live directive wire format, and the
tracker/engine integration seams (topology-reply trailing fields,
directive-aware dispatch, /metrics + /status exposure).  The
end-to-end closed-loop gate is the slow ``tools/soak.py --adapt``
scenario.
"""
import json
import socket
import threading

import pytest

from rabit_tpu import obs
from rabit_tpu import sched
from rabit_tpu.obs.adapt import (AdaptiveController, ScheduleScorer,
                                 candidate_schedules)
from rabit_tpu.sched import topo

pytestmark = pytest.mark.adapt


def _costs(**by_sched):
    """{(sched, 4096): {...}} fold from sched=(mean_ms, n) kwargs."""
    return {(s, 4096): {"mean_sec": m / 1e3, "n": n}
            for s, (m, n) in by_sched.items()}


def _feed(sm, sched, nbytes, dur, n, seq0=0, rank_late=None,
          late=0.0):
    """Feed n merged 2-rank ops of one schedule into a SpanMerger."""
    for i in range(n):
        t0 = 100.0 + i
        for rank in (0, 1):
            b = t0 + (late if rank == rank_late else 0.0)
            sm.add(rank, [[seq0 + i, 0, 0, "allreduce", sched, nbytes,
                           b, b + dur]], world=2)


# ------------------------------------------------------------ candidates
def test_candidate_schedules_mirror_applies_rules():
    assert candidate_schedules(4, [0, 0, 1, 1]) == \
        ["tree", "ring", "halving", "swing", "hier"]
    # non-pow2 world: no swing; single group: no hier
    assert candidate_schedules(6, [0, 0, 0, 1, 1, 1]) == \
        ["tree", "ring", "halving", "hier"]
    assert candidate_schedules(4, [0, 0, 0, 0]) == \
        ["tree", "ring", "halving", "swing"]
    assert candidate_schedules(4, None) == \
        ["tree", "ring", "halving", "swing"]
    assert candidate_schedules(1, [0]) == []


# ----------------------------------------------------------- cost fold
def test_span_merger_sched_cost_fold():
    sm = obs.SpanMerger(min_ops=1)
    _feed(sm, "ring", 300000, 0.050, 5)
    _feed(sm, "swing", 300000, 0.010, 3, seq0=100)
    costs = sm.sched_costs()
    bucket = obs.payload_bucket(300000)
    assert bucket == 262144
    assert costs[("ring", bucket)]["n"] == 5
    assert costs[("ring", bucket)]["mean_sec"] == pytest.approx(0.050)
    assert costs[("swing", bucket)]["mean_sec"] == pytest.approx(0.010)
    # different payloads land in different buckets
    _feed(sm, "ring", 4 << 20, 0.2, 2, seq0=500)
    assert ("ring", 4 << 20) in sm.sched_costs()


def test_payload_bucket_floor_pow2():
    assert obs.payload_bucket(1) == 1
    assert obs.payload_bucket(4096) == 4096
    assert obs.payload_bucket(4097) == 4096
    assert obs.payload_bucket(524288) == 524288
    assert obs.payload_bucket(0) == 1  # defensive floor


# -------------------------------------------------------------- scorer
def test_scorer_min_sample_gating():
    """No decision off 2 ops: an under-sampled incumbent holds, an
    under-sampled challenger is probed, never switched to."""
    sc = ScheduleScorer(["tree", "ring"], min_samples=6, margin=0.1)
    kind, _, evd = sc.decide(_costs(tree=(10, 2)), 4096, "tree")
    assert kind == "hold" and evd["why"] == "incumbent-samples"
    # incumbent full, challenger at 2 samples: probe it, don't judge it
    kind, s, _ = sc.decide(_costs(tree=(10, 8), ring=(1, 2)),
                           4096, "tree")
    assert (kind, s) == ("probe", "ring")


def test_scorer_switch_needs_the_margin():
    """Hysteresis: a challenger inside the margin holds; one beyond it
    switches, with the evidence recorded."""
    sc = ScheduleScorer(["tree", "ring"], min_samples=4, margin=0.2)
    # 10ms vs 9ms: 9 * 1.2 = 10.8 > 10 -> inside the margin, hold
    kind, _, _ = sc.decide(_costs(tree=(10, 8), ring=(9, 8)),
                           4096, "tree")
    assert kind == "hold"
    # 10ms vs 5ms: clearly beyond the margin -> switch
    kind, s, evd = sc.decide(_costs(tree=(10, 8), ring=(5, 8)),
                             4096, "tree")
    assert (kind, s) == ("switch", "ring")
    assert evd["incumbent"] == "tree"
    assert evd["challenger_sec"] < evd["incumbent_sec"]
    assert evd["samples"] == {"tree": 8, "ring": 8}


def test_scorer_no_flapping_on_noisy_costs():
    """After a switch the roles flip: noise within the margin can never
    switch back — flapping needs both directions to leap-frog by the
    margin."""
    sc = ScheduleScorer(["tree", "ring"], min_samples=4, margin=0.2)
    # ring won; tree drifts slightly better than ring within the margin
    for tree_ms in (9.5, 9.0, 8.7, 9.3):
        kind, _, _ = sc.decide(_costs(tree=(tree_ms, 8), ring=(9.2, 8)),
                               4096, "ring")
        assert kind == "hold", tree_ms


def test_scorer_determinism():
    """The same fold yields the same verdict, every time — decisions
    replay."""
    sc = ScheduleScorer(["tree", "ring", "halving"], 4, 0.15)
    fold = _costs(tree=(10, 8), ring=(4, 8), halving=(4, 8))
    verdicts = {sc.decide(fold, 4096, "tree")[0:2] for _ in range(10)}
    assert len(verdicts) == 1
    # equal means tie-break on candidate order: ring precedes halving
    assert verdicts == {("switch", "ring")}


def test_scorer_banned_candidates_skipped():
    sc = ScheduleScorer(["tree", "ring", "swing"], 4, 0.1)
    fold = _costs(tree=(10, 8))
    kind, s, _ = sc.decide(fold, 4096, "tree",
                           banned={"ring", "swing"})
    assert kind == "hold"  # nothing left to probe, nothing measured


# ---------------------------------------------------------- controller
def test_controller_probe_then_switch_lifecycle():
    """The full exploration arc on a live SpanMerger: probes walk the
    unmeasured candidates in order, then the measured winner takes the
    switch — with the evidence and counters recorded."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=3, margin=0.1)
    assert ctl.candidates == ["tree", "ring", "halving", "swing"]
    _feed(sm, "tree", 4096, 0.030, 4)           # the static incumbent
    acts = ctl.tick(sm, {})
    assert [a.kind for a in acts] == ["probe"]
    assert acts[0].sched == "ring" and ctl.active[4096] == "ring"
    assert ctl.tick(sm, {}) == []               # probe window filling
    _feed(sm, "ring", 4096, 0.025, 3, seq0=50)
    acts = ctl.tick(sm, {})
    assert [a.kind for a in acts] == ["probe"]  # next candidate
    assert acts[0].sched == "halving"
    _feed(sm, "halving", 4096, 0.010, 3, seq0=90)
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.sched) for a in acts] == [("probe", "swing")]
    _feed(sm, "swing", 4096, 0.020, 3, seq0=130)
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.sched) for a in acts] == [("switch", "halving")]
    evd = acts[0].evidence
    assert evd["incumbent"] == "tree"
    assert evd["challenger_sec"] < evd["incumbent_sec"]
    assert ctl.active[4096] == "halving"
    assert ctl.counters["probe"] == 3 and ctl.counters["switch"] == 1
    # steady state: no further actions on the same fold
    assert ctl.tick(sm, {}) == []


def test_controller_settles_back_after_losing_probe():
    """A probe that measured WORSE must not stick: the controller
    settles the directive back on the incumbent (still a push — the
    workers run the loser right now)."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=3, margin=0.5)
    _feed(sm, "tree", 4096, 0.010, 4)
    assert [a.sched for a in ctl.tick(sm, {})] == ["ring"]
    _feed(sm, "ring", 4096, 0.011, 3, seq0=50)      # ring loses
    acts = ctl.tick(sm, {})
    assert [a.kind for a in acts] == ["probe"]      # halving next
    _feed(sm, "halving", 4096, 0.012, 3, seq0=90)   # halving loses too
    assert [a.sched for a in ctl.tick(sm, {})] == ["swing"]
    _feed(sm, "swing", 4096, 0.013, 3, seq0=130)    # swing loses too
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.sched) for a in acts] == [("settle", "tree")]
    assert ctl.active[4096] == "tree"


def test_controller_rebuild_resets_cross_world_evidence():
    """A membership change rebuilds the controller AND drops the span
    merger's rolling windows: timings/lateness measured at the old
    world (old rank numbering) must not feed the new world's
    decisions, cache merges or demotions."""
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2)
    t._adapt = True
    try:
        job = t._admit("rw", 2)
        job._members = {"0", "1"}
        job._rank_of = {"0": 0, "1": 1}
        job._last_groups = [0, 1]
        _feed(job._spans, "tree", 4096, 0.030, 20)
        job._adapt_tick()                      # builds the controller
        assert job._spans.sched_costs()        # world-2 evidence held
        # the world changes (elastic rescale completed a new round)
        job.n_workers = 3
        job._last_groups = [0, 0, 1]
        with job._scale_lock:
            job._target_world = None           # round already landed
        job._sched_switch_pending = False
        job._adapt_tick()                      # rebuild
        assert job._controller.world == 3
        assert job._spans.sched_costs() == {}  # old-world windows gone
    finally:
        t.stop()
        t._close_all()


def test_controller_seeded_settled_still_settles_back():
    """A rebuilt controller (tracker restart / membership change) is
    seeded with the journaled directive as its settled choice; a
    losing probe afterwards must STILL settle the directive back —
    the workers must never stay pinned on the worst probed schedule
    just because 'settled' was pre-populated."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=3, margin=0.5)
    ctl.active = {4096: "ring"}
    ctl.settled = {4096: "ring"}        # the JobState rebuild seeding
    _feed(sm, "ring", 4096, 0.010, 4)
    assert [a.sched for a in ctl.tick(sm, {})] == ["tree"]
    _feed(sm, "tree", 4096, 0.050, 3, seq0=50)      # tree loses 5x
    acts = ctl.tick(sm, {})
    assert [a.sched for a in acts if a.kind == "probe"] == ["halving"]
    _feed(sm, "halving", 4096, 0.050, 3, seq0=90)
    assert [a.sched for a in ctl.tick(sm, {})] == ["swing"]
    _feed(sm, "swing", 4096, 0.050, 3, seq0=130)
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.sched) for a in acts] == [("settle", "ring")]
    assert ctl.active[4096] == "ring"   # NOT the last losing probe


def test_controller_ghost_incumbent_falls_back_to_observed():
    """A settled schedule that left the candidate set (e.g. hier after
    the host groups collapsed) must not wedge adaptation on a
    'no-incumbent' hold forever: the controller falls back to the
    observed incumbent and keeps exploring."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=3, margin=0.1)
    assert "hier" not in ctl.candidates          # flat topology
    ctl.active = {4096: "hier"}
    ctl.settled = {4096: "hier"}                 # journaled ghost
    _feed(sm, "tree", 4096, 0.030, 4)
    acts = ctl.tick(sm, {})
    assert [a.kind for a in acts] == ["probe"]   # not wedged


def test_controller_probe_timeout_bans_unrunnable_schedule():
    """A probe that never yields one sample (engine applies() fell
    back) is abandoned and banned for the bucket instead of wedging
    exploration."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=2, margin=0.1)
    _feed(sm, "tree", 4096, 0.010, 3)
    assert [a.sched for a in ctl.tick(sm, {})] == ["ring"]
    # merged ops advance but 'ring' never reports a span
    _feed(sm, "tree", 4096, 0.010, 40, seq0=100)
    acts = ctl.tick(sm, {})  # ban fires, next candidate probed
    assert ctl._banned[4096] == {"ring"}
    assert ctl.counters["probe_failed"] == 1
    # the failure is SURFACED as an action (timeline event + service
    # counter on the tracker), not just a private record
    assert [(a.kind, a.sched) for a in acts] == \
        [("probe_failed", "ring"), ("probe", "halving")]


def test_controller_probe_budget_rebased_at_epoch_adoption():
    """Long-commit-interval jobs: the ops merged BETWEEN the probe
    decision and the switch epoch actually landing must not count
    against the probe's abandonment budget — the workers only adopt
    the directive at their next commit boundary."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(2, None, min_samples=2, margin=0.1)
    _feed(sm, "tree", 4096, 0.010, 3)
    assert [a.sched for a in ctl.tick(sm, {})] == ["ring"]
    # a long stretch of incumbent ops merges while the epoch is still
    # pending (the tracker tick is paused); adoption re-baselines
    _feed(sm, "tree", 4096, 0.010, 40, seq0=100)
    ctl.note_epoch_landed(sm.merged_ops)
    assert ctl.tick(sm, {}) == []          # NOT banned: budget rebased
    assert "ring" not in ctl._banned.get(4096, set())
    _feed(sm, "ring", 4096, 0.008, 2, seq0=200)
    acts = ctl.tick(sm, {})                # probe measured normally
    assert [a.kind for a in acts] == ["probe"]  # next candidate


def test_controller_demotion_reuses_straggler_factor():
    """Demotion threshold == RABIT_STRAGGLER_FACTOR, held for
    RABIT_DEMOTE_CHECKS consecutive ticks; reinstatement below
    factor/2 for as many ticks (the straggler timeline's hysteresis).
    One noisy window never demotes."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(4, [0, 0, 1, 1], min_samples=99,
                             margin=0.1, straggler_factor=3.0,
                             demote_checks=2)
    # one over-threshold tick: streak too short, no demotion
    assert ctl.tick(sm, {0: 5.0}) == []
    # a dip resets the streak
    assert ctl.tick(sm, {0: 1.0}) == []
    assert ctl.tick(sm, {0: 5.0}) == []
    acts = ctl.tick(sm, {0: 4.0})
    assert [(a.kind, a.rank) for a in acts] == [("demote", 0)]
    assert ctl.demoted == {0}
    assert acts[0].evidence["factor"] == 3.0
    # between factor/2 and factor: neither demote nor reinstate
    assert ctl.tick(sm, {0: 2.0}) == []
    assert ctl.tick(sm, {0: 1.0}) == []
    acts = ctl.tick(sm, {0: 1.0})
    assert [(a.kind, a.rank) for a in acts] == [("reinstate", 0)]
    assert ctl.demoted == set()


def test_controller_reinstates_demoted_rank_without_signal():
    """A demoted rank whose spans vanished (tracker restart rebuilt
    the merger; or the rank died and a fresh worker took the slot)
    must not stay demoted forever on ABSENT evidence: no-signal ticks
    count toward reinstatement."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(4, [0, 0, 1, 1], min_samples=99,
                             margin=0.1, straggler_factor=3.0,
                             demote_checks=2)
    ctl.demoted = {3}       # seeded from the journal after a restart
    assert ctl.tick(sm, {}) == []          # one no-signal tick
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.rank) for a in acts] == [("reinstate", 3)]
    assert acts[0].evidence["why"] == "no-signal"
    assert ctl.demoted == set()


def test_controller_demotion_needs_hier():
    """Leadership only exists hierarchically: a flat-topology job never
    demotes (there is no leader role to lose)."""
    sm = obs.SpanMerger(min_ops=1)
    ctl = AdaptiveController(4, None, min_samples=99, demote_checks=1,
                             straggler_factor=3.0)
    assert "hier" not in ctl.candidates
    assert ctl.tick(sm, {0: 99.0}) == []
    assert ctl.demoted == set()


def test_controller_env_knobs(monkeypatch):
    monkeypatch.setenv("RABIT_ADAPT_MIN_SAMPLES", "7")
    monkeypatch.setenv("RABIT_ADAPT_MARGIN", "0.33")
    monkeypatch.setenv("RABIT_DEMOTE_CHECKS", "5")
    ctl = AdaptiveController(2, None, straggler_factor=4.5)
    assert ctl.min_samples == 7
    assert ctl.margin == pytest.approx(0.33)
    assert ctl.demote_checks == 5
    assert ctl.straggler_factor == 4.5
    monkeypatch.setenv("RABIT_ADAPT_MIN_SAMPLES", "junk")
    assert AdaptiveController(2, None).min_samples == 12  # default


# ------------------------------------------------- demoted-aware hier
def test_group_leaders_exclude_demoted():
    groups = [0, 0, 1, 1]
    assert topo.group_leaders(groups) == [0, 2]
    assert topo.group_leaders(groups, {0}) == [1, 2]
    assert topo.group_leader(groups, 0, {0}) == 1
    # a fully-demoted group degrades to the plain minimum rank
    assert topo.group_leaders(groups, {0, 1}) == [0, 2]
    # member links follow the elected leader
    assert topo.hier_peers(0, 4, groups, {0}) == {1}
    assert 1 in topo.hier_peers(2, 4, groups, {0})
    # the union handout keeps BOTH elections' links wired
    assert {1, 2} <= topo.extra_link_peers(0, 4, groups, {0})


# ----------------------------------------------------- tuner additions
def test_tuning_cache_online_merge_round_trip(tmp_path):
    cache = sched.TuningCache({}, {"host": "h"})
    cache.merge_online("allreduce", 4, 262144, "swing")
    cache.merge_online("allreduce", 4, 1 << 20, "hier")
    cache.merge_online("allreduce", 8, 262144, "halving")
    cache.save(str(tmp_path))
    loaded = sched.TuningCache.load(str(tmp_path))
    assert loaded is not None
    assert loaded.pick("allreduce", 262144, 4) == "swing"
    assert loaded.pick("allreduce", 1 << 20, 4) == "hier"
    assert loaded.pick("allreduce", 262144, 8) == "halving"
    assert loaded.meta["online_merges"] == 3
    # online merges widen WORLD coverage: world 6 rides nearest-world
    assert loaded.pick("allreduce", 262144, 6) in ("swing", "halving")
    # ...but a SPARSE neighbor row must not answer wildly different
    # payload sizes: beyond two octaves the fallback misses to static
    # (a 64B op must not ride a schedule learned at 512KB)
    assert loaded.pick("allreduce", 64, 6) is None
    assert loaded.pick("allreduce", 64, 4) == "swing"  # exact world:
    # the original unbounded nearest-size semantics are unchanged


def test_directive_wire_format_round_trip():
    table = {262144: "halving", 4 << 20: "hier"}
    raw = sched.encode_directive(table)
    assert sched.decode_directive(raw) == table
    # garbage tolerance: junk entries skipped, never raised
    assert sched.decode_directive("x:y,:,9,-3:tree,1024:ring") == \
        {1024: "ring"}
    assert sched.decode_directive("") == {}
    # nearest-bucket pick in log space, capped at two octaves: a small
    # op must not ride the dominant bucket's bandwidth schedule
    assert sched.directive_pick(table, 300000) == "halving"
    assert sched.directive_pick(table, 16 << 20) == "hier"
    assert sched.directive_pick({524288: "ring"}, 4096) is None
    assert sched.directive_pick({}, 1024) is None


# -------------------------------------------------- protocol trailing
def test_topology_reply_adaptive_fields_round_trip():
    from rabit_tpu.tracker import protocol as P

    reply = P.TopologyReply(rank=1, world=4, parent=0, neighbors=[0],
                            ring_prev=0, ring_next=2, epoch=3,
                            groups=[0, 0, 1, 1],
                            sched="524288:swing", demoted=[0])
    a, b = socket.socketpair()
    try:
        t = threading.Thread(target=reply.send, args=(a,))
        t.start()
        got = P.TopologyReply.recv(b)
        t.join()
        assert got.sched == "524288:swing"
        assert got.demoted == [0]
        assert got.groups == [0, 0, 1, 1] and got.epoch == 3
    finally:
        a.close()
        b.close()


def test_topology_reply_tolerates_pre_adaptive_tracker():
    """A pre-adaptive tracker stops after the groups field and closes —
    the reader must default to no directive, not die at EOF."""
    from rabit_tpu.tracker import protocol as P

    reply = P.TopologyReply(rank=1, world=2, parent=0, neighbors=[0],
                            ring_prev=0, ring_next=0, epoch=1,
                            groups=[0, 0], sched="1024:ring",
                            demoted=[1])
    a, b = socket.socketpair()
    try:
        import io
        import struct

        buf = io.BytesIO()

        class _Cap:
            def sendall(self, data):
                buf.write(data)

        reply.send(_Cap())
        raw = buf.getvalue()
        # truncate exactly the adaptive trailing fields: str(sched) is
        # 4 + len bytes, demoted is 4 + 4*len
        old_wire = raw[:len(raw) - (4 + len("1024:ring")) - (4 + 4)]
        a.sendall(old_wire)
        a.close()
        got = P.TopologyReply.recv(b)
        assert got.sched == "" and got.demoted == []
        assert got.groups == [0, 0] and got.epoch == 1
    finally:
        b.close()


def test_topology_reply_midfield_truncation_raises():
    """A reply cut INSIDE the trailing fields (reset mid-send) is a
    failed registration to retry, NOT an old-layout default: one rank
    silently dropping the directive its peers adopted would break the
    schedule pick's collective-decision invariant."""
    import io

    from rabit_tpu.tracker import protocol as P

    reply = P.TopologyReply(rank=1, world=2, parent=0, neighbors=[0],
                            ring_prev=0, ring_next=0, epoch=1,
                            groups=[0, 0], sched="1024:ring",
                            demoted=[1])
    buf = io.BytesIO()

    class _Cap:
        def sendall(self, data):
            buf.write(data)

    reply.send(_Cap())
    raw = buf.getvalue()
    # cut 3 bytes into the sched string's payload
    cut = len(raw) - len("1024:ring") - (4 + 4) + 3
    a, b = socket.socketpair()
    try:
        a.sendall(raw[:cut])
        a.close()
        with pytest.raises(OSError):
            P.TopologyReply.recv(b)
    finally:
        b.close()


# -------------------------------------------------- engine dispatch
def test_pick_schedule_honors_live_directive():
    from rabit_tpu.engine.pysocket import PySocketEngine

    eng = PySocketEngine()
    eng._world = 4
    eng._rank = 0
    eng._links = {1: object(), 2: object(), 3: object()}
    eng._sched_live = {4096: "halving"}
    eng._sched_name = "static"
    assert eng._pick_schedule(4096, 0).name == "halving"
    # nearest bucket in log space, like the tuning cache
    assert eng._pick_schedule(6000, 0).name == "halving"
    # an explicitly FORCED schedule is never overridden
    eng._sched_name = "ring"
    assert eng._pick_schedule(4096, 0).name == "ring"
    # a directive naming a schedule that cannot run falls back
    eng._sched_name = "static"
    eng._sched_live = {4096: "hier"}     # no groups: hier can't apply
    assert eng._pick_schedule(4096, 0).name == "tree"
    # unknown names from a newer tracker fall back too
    eng._sched_live = {4096: "warp-drive"}
    assert eng._pick_schedule(4096, 0).name == "tree"


# ---------------------------------------------- tracker integration
def test_tracker_adapt_tick_pushes_switch_epoch_and_exposes_it():
    """A bare multi-tenant tracker with the controller armed: synthetic
    spans drive a probe decision; the push arms a same-world rescale
    epoch, /metrics exposes rabit_sched_active +
    rabit_controller_decisions_total, /status carries the decision
    records, and the journal round-trips the learned state."""
    import urllib.request

    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, obs_port=0)
    t._adapt = True
    try:
        job = t._admit("adaptive", 2)
        job._members = {"0", "1"}
        job._rank_of = {"0": 0, "1": 1}
        job._last_groups = [0, 1]
        sm = job._spans
        _feed(sm, "tree", 4096, 0.030, 20)
        job._adapt_tick()
        ctl = job._controller
        assert ctl is not None
        assert [d.kind for d in ctl.decisions] == ["probe"]
        # the push armed a SAME-world epoch for the next round
        with job._scale_lock:
            assert job._target_world == 2
        assert job._sched_switch_pending
        assert job._active_sched  # the probe's directive is live
        # one pending epoch at a time: no second decision until it lands
        job._adapt_tick()
        assert len(ctl.decisions) == 1
        # exposure
        with urllib.request.urlopen(
                f"http://127.0.0.1:{t.obs_port}/metrics", timeout=3) as r:
            metrics = r.read().decode()
        assert 'rabit_sched_active{bucket="4096",job="adaptive"' \
            in metrics
        assert ('rabit_controller_decisions_total{job="adaptive",'
                'kind="probe"} 1') in metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{t.obs_port}/status", timeout=3) as r:
            status = json.loads(r.read().decode())
        ctl_s = status["jobs"]["adaptive"]["controller"]
        assert ctl_s["decisions"][-1]["kind"] == "probe"
        assert ctl_s["active_sched"]
    finally:
        t.stop()
        t._close_all()


def test_jobstate_journal_round_trips_adaptive_state(tmp_path):
    """A restarted tracker must keep handing out the learned directive
    and demotion set (the controller's windows rebuild live, but what
    it DECIDED is control-plane state like the rank map)."""
    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.tracker.tracker import JobState, Tracker

    t = Tracker.__new__(Tracker)
    job = JobState(t, "default", 2)
    job.attach_store(ckpt_mod.CheckpointStore(str(tmp_path), rank=0))
    job._members = {"0", "1"}
    job._active_sched = {524288: "swing"}
    job._demoted = {1}
    job._journal()

    job2 = JobState(t, "default", 2)
    job2.attach_store(ckpt_mod.CheckpointStore(str(tmp_path), rank=0))
    assert job2.restore_journal()
    assert job2._active_sched == {524288: "swing"}
    assert job2._demoted == {1}


def test_tracker_tune_merge_persists(tmp_path):
    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker(2, tune_dir=str(tmp_path))
    try:
        t._tune_merge("allreduce", 4, 262144, "swing")
        loaded = sched.TuningCache.load(str(tmp_path))
        assert loaded is not None
        assert loaded.pick("allreduce", 262144, 4) == "swing"
    finally:
        t.stop()
        t._close_all()


# ---------------------------------------- codec-override emission (15)
def _feed_wire(sm, sched, nbytes, dur, n, wire, seq0=0):
    """Feed n merged 2-rank ops carrying an explicit wire label (the
    9-field span form PR 13 introduced)."""
    for i in range(n):
        t0 = 100.0 + i
        for rank in (0, 1):
            sm.add(rank, [[seq0 + i, 0, 0, "allreduce", sched, nbytes,
                           t0, t0 + dur, wire]], world=2)


def test_scorer_codec_override_needs_margin_and_samples():
    """The emission core is pure and hysteretic like schedule
    switches: a quantized wire must beat full width by the margin with
    min_samples on BOTH sides, else no override."""
    sc = ScheduleScorer(["tree", "ring"], min_samples=4, margin=0.15)
    beats = {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 8},
             ("ring", 4096, "int8"): {"mean_sec": 0.005, "n": 8}}
    codec, evd = sc.codec_override(beats, 4096, "ring")
    assert codec == "int8"
    assert evd["codec_sec"] < evd["base_sec"]
    # inside the margin: held (no flap)
    close = {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 8},
             ("ring", 4096, "int8"): {"mean_sec": 0.0095, "n": 8}}
    assert sc.codec_override(close, 4096, "ring")[0] is None
    # starving either side blocks the verdict
    thin = {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 2},
            ("ring", 4096, "int8"): {"mean_sec": 0.005, "n": 8}}
    assert sc.codec_override(thin, 4096, "ring")[0] is None
    none_evd = sc.codec_override(
        {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 8}},
        4096, "ring")
    assert none_evd == (None, {"why": "no-codec-evidence"})
    # the cheapest of several measured codecs wins
    multi = dict(beats)
    multi[("ring", 4096, "int4")] = {"mean_sec": 0.003, "n": 8}
    assert sc.codec_override(multi, 4096, "ring")[0] == "int4"


def test_controller_emits_codec_override_behind_flag():
    """RABIT_ADAPT_CODEC: with the flag on, codec-scoped span evidence
    turns the settled bucket's directive entry into the slashed
    ``sched/codec`` form — recorded as a ``codec`` decision; the flag
    off never emits; fading evidence reverts to the plain entry."""
    sm = obs.SpanMerger(min_ops=1)
    # world 3: tree/ring/halving — all measured, ring settled winner
    ctl = AdaptiveController(3, None, min_samples=3, margin=0.1,
                             adapt_codec=True)
    ctl.settled[4096] = "ring"
    ctl.active[4096] = "ring"
    _feed(sm, "tree", 4096, 0.030, 3)
    _feed(sm, "ring", 4096, 0.010, 3, seq0=40)
    _feed(sm, "halving", 4096, 0.020, 3, seq0=80)
    assert ctl.tick(sm, {}) == []     # full-width only: nothing to emit
    _feed_wire(sm, "ring", 4096, 0.004, 3, "int8", seq0=120)
    acts = ctl.tick(sm, {})
    assert [(a.kind, a.sched) for a in acts] == [("codec", "ring/int8")]
    assert ctl.active[4096] == "ring/int8"
    assert ctl.settled[4096] == "ring"     # settled stays plain
    assert ctl.tick(sm, {}) == []          # stable: no re-emission
    # the settle-back guard treats sched/codec as the incumbent, so a
    # slashed directive never reads as a leftover probe
    assert ctl.counters.get("settle", 0) == 0

    # flag off: the same evidence emits nothing
    ctl2 = AdaptiveController(3, None, min_samples=3, margin=0.1,
                              adapt_codec=False)
    ctl2.settled[4096] = "ring"
    ctl2.active[4096] = "ring"
    assert ctl2.tick(sm, {}) == []


def test_controller_codec_env_flag(monkeypatch):
    monkeypatch.setenv("RABIT_ADAPT_CODEC", "1")
    assert AdaptiveController(2, None).adapt_codec
    monkeypatch.setenv("RABIT_ADAPT_CODEC", "0")
    assert not AdaptiveController(2, None).adapt_codec
    monkeypatch.delenv("RABIT_ADAPT_CODEC")
    assert not AdaptiveController(2, None).adapt_codec


def test_slashed_directive_round_trips_through_tracker_state(tmp_path):
    """A journaled ``sched/codec`` directive survives a tracker
    restart, still decodes into (schedule, codec) halves on the wire
    form, and seeds the rebuilt controller's settled map with the
    PLAIN schedule name only."""
    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.sched import tuner
    from rabit_tpu.tracker.tracker import JobState, Tracker

    t = Tracker.__new__(Tracker)
    job = JobState(t, "default", 2)
    job.attach_store(ckpt_mod.CheckpointStore(str(tmp_path), rank=0))
    job._members = {"0", "1"}
    job._active_sched = {262144: "ring/int8"}
    job._journal()

    job2 = JobState(t, "default", 2)
    job2.attach_store(ckpt_mod.CheckpointStore(str(tmp_path), rank=0))
    assert job2.restore_journal()
    assert job2._active_sched == {262144: "ring/int8"}
    directive = tuner.encode_directive(job2._active_sched)
    table = tuner.decode_directive(directive)
    assert tuner.directive_entry(table, 262144) == ("ring", "int8")
    # the rebuilt controller seeds settled with the plain half
    job2._last_groups = []
    job2._adapt_tick()  # builds the controller (no spans: no actions)
    assert job2._controller.settled == {262144: "ring"}
    assert job2._controller.active == {262144: "ring/int8"}


def test_codec_override_revert_is_hysteretic():
    """Review-driven: emit needs beat-by-margin, but an EMITTED
    override only reverts once the codec stops beating full width at
    all — a cost hovering at the margin boundary cannot flap the
    directive (each flap costs the world an epoch)."""
    sc = ScheduleScorer(["ring"], min_samples=4, margin=0.15)
    hover = {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 8},
             ("ring", 4096, "int8"): {"mean_sec": 0.0092, "n": 8}}
    # inside the margin: not enough to EMIT...
    assert sc.codec_override(hover, 4096, "ring")[0] is None
    # ...but enough to HOLD an already-emitted override
    codec, evd = sc.codec_override(hover, 4096, "ring",
                                   incumbent_codec="int8")
    assert codec == "int8" and evd.get("held") == "int8"
    # genuinely worse than full width: the incumbent reverts
    worse = {("ring", 4096, "none"): {"mean_sec": 0.010, "n": 8},
             ("ring", 4096, "int8"): {"mean_sec": 0.011, "n": 8}}
    assert sc.codec_override(worse, 4096, "ring",
                             incumbent_codec="int8")[0] is None
