"""Public API on the single-process (empty) engine.

Mirrors the reference's bring-up path: guide/basic.cc and
src/engine_empty.cc — programs written against the full API must run
unmodified in a world of one.
"""
import numpy as np
import pytest

import rabit_tpu
from rabit_tpu.ops import ReduceOp, dtype_to_enum, enum_to_dtype


def test_init_identity(empty_engine):
    assert rabit_tpu.get_rank() == 0
    assert rabit_tpu.get_world_size() == 1
    assert not rabit_tpu.is_distributed()
    assert isinstance(rabit_tpu.get_processor_name(), str)


def test_allreduce_inplace(empty_engine):
    a = np.arange(10, dtype=np.float32)
    out = rabit_tpu.allreduce(a, rabit_tpu.SUM)
    assert out is a
    np.testing.assert_array_equal(out, np.arange(10, dtype=np.float32))


def test_allreduce_prepare_fun_called(empty_engine):
    called = []
    a = np.zeros(4, dtype=np.int32)

    def prep():
        called.append(True)
        a[:] = 7

    rabit_tpu.allreduce(a, rabit_tpu.MAX, prepare_fun=prep)
    assert called == [True]
    assert (a == 7).all()


def test_allreduce_scalar(empty_engine):
    out = rabit_tpu.allreduce(3.5, rabit_tpu.SUM)
    assert float(out) == 3.5


def test_broadcast_object(empty_engine):
    obj = {"w": [1, 2, 3], "name": "model"}
    got = rabit_tpu.broadcast(obj, root=0)
    assert got == obj


def test_allgather(empty_engine):
    a = np.array([1.0, 2.0], dtype=np.float64)
    g = rabit_tpu.allgather(a)
    assert g.shape == (1, 2)
    np.testing.assert_array_equal(g[0], a)


def test_checkpoint_roundtrip(empty_engine):
    version, model = rabit_tpu.load_checkpoint()
    assert version == 0 and model is None
    rabit_tpu.checkpoint({"iter": 1})
    assert rabit_tpu.version_number() == 1
    version, model = rabit_tpu.load_checkpoint()
    assert version == 1 and model == {"iter": 1}


def test_lazy_checkpoint(empty_engine):
    rabit_tpu.lazy_checkpoint([9, 9])
    version, model = rabit_tpu.load_checkpoint()
    assert version == 1 and model == [9, 9]


def test_local_checkpoint(empty_engine):
    rabit_tpu.checkpoint({"g": 1}, {"l": 2})
    version, g, l = rabit_tpu.load_checkpoint(with_local=True)
    assert (version, g, l) == (1, {"g": 1}, {"l": 2})


def test_double_init_rejected(empty_engine):
    with pytest.raises(rabit_tpu.RabitError):
        rabit_tpu.init(rabit_engine="empty")


def test_dtype_enum_roundtrip():
    for dt in ["int8", "uint8", "int32", "uint32", "int64", "uint64",
               "float32", "float64", "float16"]:
        code = dtype_to_enum(dt)
        assert enum_to_dtype(code) == np.dtype(dt)


def test_reduce_ops_numpy():
    from rabit_tpu.ops.reduce_ops import apply_op_numpy

    a = np.array([1, 5, 3], dtype=np.int32)
    b = np.array([4, 2, 3], dtype=np.int32)
    np.testing.assert_array_equal(
        apply_op_numpy(ReduceOp.MAX, a.copy(), b), [4, 5, 3])
    np.testing.assert_array_equal(
        apply_op_numpy(ReduceOp.MIN, a.copy(), b), [1, 2, 3])
    np.testing.assert_array_equal(
        apply_op_numpy(ReduceOp.SUM, a.copy(), b), [5, 7, 6])
    np.testing.assert_array_equal(
        apply_op_numpy(ReduceOp.BITOR, a.copy(), b), [5, 7, 3])


def test_checkpoint_serializable_roundtrip(empty_engine):
    """Custom-Serializable checkpoints restore through into_global."""
    from rabit_tpu.utils import Serializable

    class Model(Serializable):
        def __init__(self, n=0):
            self.n = n

        def save(self, stream):
            stream.write_u64(self.n)

        def load(self, stream):
            self.n = stream.read_u64()

    rabit_tpu.checkpoint(Model(7))
    version, m = rabit_tpu.load_checkpoint(into_global=Model())
    assert version == 1 and m.n == 7
    # loading without an instance is a clear error, not an unpickle crash
    with pytest.raises(rabit_tpu.RabitError):
        rabit_tpu.load_checkpoint()


def test_checkpoint_raw_bytes_roundtrip(empty_engine):
    rabit_tpu.checkpoint(b"\x00\x01raw")
    version, m = rabit_tpu.load_checkpoint()
    assert version == 1 and m == b"\x00\x01raw"
