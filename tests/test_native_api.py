"""Compile-and-run test for the public C++ API header
(rabit_tpu/native/include/rabit_tpu/rabit_tpu.h — the reference's
include/rabit.h equivalent)."""
import pathlib
import subprocess

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
NATIVE = ROOT / "rabit_tpu" / "native"


def test_cpp_api_smoke(native_lib, tmp_path):
    exe = tmp_path / "api_smoke"
    build = subprocess.run(
        ["g++", "-std=c++17", "-O1", "-Wall", "-Wextra", "-Werror",
         f"-I{NATIVE / 'include'}",
         str(ROOT / "tests" / "native" / "api_smoke.cc"),
         str(native_lib), f"-Wl,-rpath,{native_lib.parent}",
         "-o", str(exe)],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=60)
    assert run.returncode == 0, run.stderr
    assert "api_smoke OK" in run.stdout
