"""Compile-and-run test for the public C++ API header
(rabit_tpu/native/include/rabit_tpu/rabit_tpu.h — the reference's
include/rabit.h equivalent)."""
import pathlib
import subprocess

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
NATIVE = ROOT / "rabit_tpu" / "native"


def _build(native_lib, tmp_path, name):
    exe = tmp_path / name
    build = subprocess.run(
        ["g++", "-std=c++17", "-O1", "-Wall", "-Wextra", "-Werror",
         f"-I{NATIVE / 'include'}",
         str(ROOT / "tests" / "native" / f"{name}.cc"),
         str(native_lib), f"-Wl,-rpath,{native_lib.parent}",
         "-o", str(exe)],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    return exe


def test_cpp_api_smoke(native_lib, tmp_path):
    exe = _build(native_lib, tmp_path, "api_smoke")
    run = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=60)
    assert run.returncode == 0, run.stderr
    assert "api_smoke OK" in run.stdout


def test_cpp_custom_reducers_multiworker(native_lib, tmp_path):
    """Reducer<> and SerializeReducer<> across a 3-worker native job
    (reference: ReduceHandle surface, include/rabit.h:236-326)."""
    from rabit_tpu.tracker.launch_local import launch

    exe = _build(native_lib, tmp_path, "custom_reduce")
    code = launch(3, [str(exe), "rabit_engine=native"])
    assert code == 0


def test_cpp_custom_reducers_with_fault(native_lib, tmp_path):
    """Custom reductions replay through the robust cache after a
    kill-point death (rank 1 dies at its second collective)."""
    from rabit_tpu.tracker.launch_local import launch

    exe = _build(native_lib, tmp_path, "custom_reduce")
    code = launch(3, [str(exe), "rabit_engine=mock", "mock=1,0,1,0"])
    assert code == 0
