"""Topology-aware collective schedules + auto-tuner (doc/performance.md
"Schedule selection").

The contracts pinned here:

* peer-pattern math — swing pairings are involutions with disjoint
  doubling reachability (the exactly-once-sum property), halving fold
  partners and tracker link handouts are symmetric;
* every schedule (tree/ring/halving/swing/hier) is value-exact at
  worlds 2,3,4,5,7,8 on zero-length, 1-item, odd-size and >chunk
  payloads (the ``ring_oddsize`` regression pattern, tiny
  reduce-buffer) — including the bf16 wire composition and graceful
  static fallback where a schedule does not apply;
* schedules compose with the existing machinery: fused buckets +
  halving/doubling stay parity-exact vs blocking, the async
  out-of-order guard holds on the new pumps, a chaos mid-stream reset
  recovers on each new schedule, and pyrobust kill-point replay serves
  halving/doubling streams bit-exactly;
* the tuning cache round-trips (schema-versioned, corrupt/mismatched
  files rejected to the static fallback) and — the slow gate —
  ``bench → cache → rabit_sched=auto`` picks the measured winner per
  point at runtime.
"""
import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.sched

SCHED_WORLDS = [2, 3, 4, 5, 7, 8]


def _groups(world: int) -> str:
    """Two simulated hosts: first half group 0, second half group 1."""
    return ",".join(str(i // ((world + 1) // 2)) for i in range(world))


def _launch(worker, world, extra_env=None, args=(), tracker_groups=None):
    from rabit_tpu.tracker.launch_local import launch

    saved = os.environ.get("RABIT_TRACKER_GROUPS")
    try:
        # The tracker runs in the launcher's process: the group
        # override must be visible THERE, not in the workers.
        if tracker_groups is not None:
            os.environ["RABIT_TRACKER_GROUPS"] = tracker_groups
        else:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        return launch(world, [sys.executable,
                              f"tests/workers/{worker}.py",
                              *map(str, args)], extra_env=extra_env or {})
    finally:
        if saved is None:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        else:
            os.environ["RABIT_TRACKER_GROUPS"] = saved


# ---------------------------------------------------------- peer math
def test_swing_pairing_is_involution_and_exact_once():
    from rabit_tpu.sched import topo

    for k in range(1, 6):
        n = 1 << k
        sets = [frozenset([r]) for r in range(n)]
        for h in range(k):
            nxt = list(sets)
            for r in range(n):
                p = topo.swing_peer(r, n, h)
                assert topo.swing_peer(p, n, h) == r, (n, h, r)
                assert not (sets[r] & sets[p]), "double-counted rank"
                nxt[r] = sets[r] | sets[p]
            sets = nxt
        assert all(s == frozenset(range(n)) for s in sets), n


def test_halving_peers_symmetric_and_folded():
    from rabit_tpu.sched import topo

    for world in SCHED_WORLDS + [6, 12]:
        m = topo.pow2_floor(world)
        for r in range(world):
            for p in topo.halving_peers(r, world):
                assert r in topo.halving_peers(p, world), (world, r, p)
        for r in range(m, world):
            assert topo.halving_peers(r, world) == {r - m}


def test_extra_link_peers_symmetric():
    from rabit_tpu.sched import topo

    for world in SCHED_WORLDS:
        groups = [i // ((world + 1) // 2) for i in range(world)]
        for r in range(world):
            for p in topo.extra_link_peers(r, world, groups):
                assert r in topo.extra_link_peers(p, world, groups), \
                    (world, r, p)


def test_hier_peers_single_group_empty():
    from rabit_tpu.sched import topo

    assert topo.hier_peers(0, 4, [0, 0, 0, 0]) == set()
    peers = topo.hier_peers(0, 4, [0, 0, 1, 1])
    assert 1 in peers  # leader links its member


# ------------------------------------------------ schedule synthesis
def test_synth_cycle_stays_on_wired_edges():
    """Every synthesized cycle — flat, contiguous, interleaved groups,
    pow2 and ragged worlds — is a permutation whose consecutive edges
    all exist in the always-wired set (ring ∪ halving ∪ swing), so the
    runtime never needs a link the tracker did not hand out."""
    from rabit_tpu.sched.synth import synthesize, wired_edges

    for world in SCHED_WORLDS + [6, 9]:
        edges = wired_edges(world)
        for groups in (None,
                       [i // ((world + 1) // 2) for i in range(world)],
                       [i % 2 for i in range(world)]):
            perm = synthesize(world, groups)["perm"]
            assert sorted(perm) == list(range(world))
            for i in range(world):
                u, v = perm[i], perm[(i + 1) % world]
                assert (min(u, v), max(u, v)) in edges, \
                    (world, groups, perm)


def test_synth_beats_identity_ring_on_interleaved_placement():
    """The point of the search: on an interleaved placement the
    synthesized cycle crosses hosts fewer times than the identity
    ring, and never costs more on any placement."""
    from rabit_tpu.sched.synth import synthesize

    r = synthesize(4, [0, 1, 0, 1])
    assert r["cost"] < r["ring_cost"] and r["cross_edges"] == 2
    for world in SCHED_WORLDS + [6, 9]:
        for groups in (None, [i % 2 for i in range(world)],
                       [i // ((world + 1) // 2) for i in range(world)]):
            r = synthesize(world, groups)
            assert r["cost"] <= r["ring_cost"], (world, groups, r)


def test_synth_deterministic_and_canonical():
    """Replicated inputs → identical cycle on every rank (the search is
    the collective decision), starting at rank 0 in the canonical
    direction."""
    from rabit_tpu.sched.synth import synthesize

    groups = [i % 3 for i in range(9)]
    a = synthesize(9, groups)
    assert a == synthesize(9, list(groups))
    assert a["perm"][0] == 0


def test_synth_plan_pins_and_validates(tmp_path):
    """A plan's precomputed perm short-circuits the search; a
    non-permutation is a loud config error; the offline CLI round-trips
    through a file the runtime loader accepts."""
    import json

    from rabit_tpu.sched.synth import load_plan, main, synthesize
    from rabit_tpu.utils import RabitError

    r = synthesize(4, [0, 1, 0, 1], {"perm": [0, 2, 1, 3]})
    assert r["perm"] == [0, 2, 1, 3]
    with pytest.raises(RabitError, match="permutation"):
        synthesize(4, None, {"perm": [0, 0, 1, 3]})
    with pytest.raises(RabitError, match="chunks"):
        synthesize(4, None, {"chunks": 0})
    out = tmp_path / "plan.json"
    assert main(["--world", "4", "--groups", "0,1,0,1",
                 "--out", str(out)]) == 0
    plan = load_plan(str(out))
    assert plan["perm"] == [0, 2, 1, 3]
    assert plan["cost"] < plan["ring_cost"]
    with pytest.raises(RabitError, match="unreadable"):
        load_plan(str(tmp_path / "nope.json"))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([1, 2]))
    with pytest.raises(RabitError, match="JSON object"):
        load_plan(str(bad))


# ------------------------------------------------- static knob + picks
def test_ring_threshold_knob_moves_the_crossover():
    from rabit_tpu.engine.pysocket import PySocketEngine

    eng = PySocketEngine()
    eng._world = 4
    assert eng._pick_schedule(64 << 10, None).name == "tree"
    assert eng._pick_schedule((64 << 10) + 1, None).name == "ring"
    eng._ring_threshold = 1 << 20
    assert eng._pick_schedule(1 << 20, None).name == "tree"
    eng._ring_threshold = 0
    assert eng._pick_schedule(1, None).name == "ring"
    eng._world = 2  # world 2: ring degenerates, tree always
    assert eng._pick_schedule(1 << 30, None).name == "tree"


def test_forced_schedule_falls_back_when_inapplicable():
    from rabit_tpu.engine.pysocket import PySocketEngine

    eng = PySocketEngine()
    eng._world = 3  # not a power of two, no links wired
    eng._sched_name = "swing"
    assert eng._pick_schedule(1 << 20, None).name in ("tree", "ring")
    eng._sched_name = "hier"  # no groups handed out
    assert eng._pick_schedule(1 << 20, None).name in ("tree", "ring")


def test_rejects_unknown_sched(empty_engine):
    from rabit_tpu.engine.pysocket import PySocketEngine
    from rabit_tpu.utils import RabitError

    eng = PySocketEngine()
    with pytest.raises(RabitError, match="rabit_sched"):
        eng.init({"rabit_sched": "frobnicate", "rabit_tracker_uri": "x",
                  "rabit_tracker_port": 1})


# --------------------------------------------------------- tuner cache
def test_tuning_cache_round_trip(tmp_path):
    from rabit_tpu.sched import TuningCache

    table = {"4096": {"tree": 50.0, "ring": 10.0, "swing": 30.0},
             "1048576": {"tree": 20.0, "ring": 80.0, "bucketed": 999.0}}
    cache = TuningCache.from_bench(
        table, 4, host="h", candidates={"tree", "ring", "swing"})
    path = cache.save(str(tmp_path))
    loaded = TuningCache.load(str(tmp_path))
    assert loaded is not None
    # exact points
    assert loaded.pick("allreduce", 4096, 4) == "tree"
    assert loaded.pick("allreduce", 1 << 20, 4) == "ring"  # not bucketed
    # nearest in log space
    assert loaded.pick("allreduce", 6000, 4) == "tree"
    assert loaded.pick("allreduce", 1 << 30, 4) == "ring"
    # a world the cache never benchmarked falls back to the NEAREST
    # bench'd world in log space (one structured-log note) instead of
    # silently dropping to static; an unknown kind is still None
    assert loaded.pick("allreduce", 4096, 8) == "tree"
    assert loaded.pick("allreduce", 1 << 20, 8) == "ring"
    assert loaded.pick("allgather", 4096, 4) is None
    # schema drift and corruption are rejected, never raised
    blob = json.loads(open(path).read())
    blob["schema"] = 999
    open(path, "w").write(json.dumps(blob))
    assert TuningCache.load(str(tmp_path)) is None
    open(path, "w").write("{not json")
    assert TuningCache.load(str(tmp_path)) is None
    assert TuningCache.load(str(tmp_path / "nope")) is None


# ------------------------------------------- parity matrix (the gate)
# Tier-1 budget (ISSUE 15 satellite): the full 5-schedule × 6-world
# matrix is ~30 subprocess launches — the heaviest block in the fast
# tier.  Fast cells keep one representative per axis: EVERY schedule
# at the flagship world 4, and EVERY world on ring (the schedule the
# fused-segmented/bucketed paths ride); the remaining cells run under
# `-m slow` (and in the slow soak gates, which sweep schedules at
# other worlds anyway).
_PARITY_FAST_SCHEDS = ["tree", "ring", "halving", "swing", "hier",
                       "synth"]
# World-axis fast representatives: the smallest world (degenerate
# single-step rings / tree-only shapes) and the largest (deepest
# trees, longest rings) on ring; the middle worlds only move the
# ragged-partition arithmetic, which 2 and 8 bracket.
_PARITY_FAST_WORLDS = [2, 8]
_PARITY_CELLS = (
    [pytest.param(s, 4, id=f"{s}-4") for s in _PARITY_FAST_SCHEDS]
    + [pytest.param("ring", w, id=f"ring-{w}")
       for w in _PARITY_FAST_WORLDS]
    + [pytest.param(s, w, id=f"{s}-{w}", marks=pytest.mark.slow)
       for s in _PARITY_FAST_SCHEDS
       for w in SCHED_WORLDS if w != 4
       and not (s == "ring" and w in _PARITY_FAST_WORLDS)]
    # synth's ISSUE-18 matrix runs worlds 2..9: 6 and 9 (not in
    # SCHED_WORLDS) complete its coverage as slow cells.
    + [pytest.param("synth", w, id=f"synth-{w}", marks=pytest.mark.slow)
       for w in (6, 9)]
)


@pytest.mark.parametrize("sched,world", _PARITY_CELLS)
def test_schedule_parity_ragged_sizes(sched, world):
    """Every schedule, every world 2..8: zero-length, 1-item, odd and
    >chunk payloads reduce exactly under a tiny reduce-buffer budget
    (swing at non-pow2 worlds and hier exercise the static fallback
    path at the same time via their applies() gates).  Non-flagship
    off-ring cells are slow-marked (tier-1 budget; see _PARITY_CELLS)."""
    assert _launch("sched_parity", world,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": sched,
                    "RABIT_REDUCE_BUFFER": "4KB"},
                   tracker_groups=_groups(world)) == 0


def test_synth_parity_on_interleaved_placement():
    """The placement where synth actually re-orders the ring (groups
    0,1,0,1 — the identity ring crosses hosts every hop): values must
    stay exact with the permuted walk under a tiny chunk budget."""
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "synth",
                    "RABIT_REDUCE_BUFFER": "4KB"},
                   tracker_groups="0,1,0,1") == 0


def test_synth_parity_with_offline_plan(tmp_path):
    """Compile-once-run-many: the offline CLI's plan JSON, pinned via
    rabit_synth_plan, drives the job (no runtime search) — parity
    holds on the planned cycle."""
    import subprocess

    plan = tmp_path / "plan.json"
    subprocess.run([sys.executable, "-m", "rabit_tpu.sched.synth",
                    "--world", "4", "--groups", "0,1,0,1",
                    "--out", str(plan)], check=True)
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "synth",
                    "RABIT_SYNTH_PLAN": str(plan),
                    "RABIT_REDUCE_BUFFER": "4KB"},
                   tracker_groups="0,1,0,1") == 0


def test_auto_without_cache_falls_back_static():
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "auto",
                    "RABIT_REDUCE_BUFFER": "4KB"}) == 0


@pytest.mark.parametrize("sched", ["halving", "swing", "synth"])
def test_schedule_bf16_wire_composition(sched):
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": sched,
                    "RABIT_WIRE_DTYPE": "bf16"}) == 0


def test_hier_parity_on_pyrobust_pod_shape():
    """launch_pod shape (2x2 groups) through the robust engine."""
    assert _launch("sched_parity", 4,
                   {"RABIT_ENGINE": "pyrobust", "RABIT_SCHED": "hier"},
                   tracker_groups="0,0,1,1") == 0


# ------------------------------- composition with existing machinery
def test_fused_bucket_halving_parity():
    """Fused-bucket + halving/doubling: the async/bucketed stream stays
    bit-identical to blocking (both ride halving, whose XOR pairing is
    position-independent — commutativity-exact like the tree)."""
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_SCHED": "halving"},
                   args=["parity"]) == 0


def test_fused_bucket_swing_parity():
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_SCHED": "swing"},
                   args=["parity"]) == 0


# Tier-1 budget: one guard cell fast (the halving pump — the XOR
# pairing is the new-pump shape); swing's rides `-m slow`.
@pytest.mark.parametrize("sched", [
    "halving", pytest.param("swing", marks=pytest.mark.slow)])
def test_async_out_of_order_guard_on_new_pumps(sched):
    assert _launch("async_worker", 4, {"RABIT_ENGINE": "pysocket",
                                       "RABIT_SCHED": sched},
                   args=["order"]) == 0


# Tier-1 budget: hier stays fast (the only schedule with leader-link
# rewiring in its recovery path); halving/swing resets ride `-m slow`.
@pytest.mark.chaos
@pytest.mark.parametrize("sched", [
    pytest.param("halving", marks=pytest.mark.slow),
    pytest.param("swing", marks=pytest.mark.slow), "hier",
    # synth stays fast: the permuted walk re-synthesizes against the
    # post-failover topology (the plan-sanitize path), which no other
    # schedule exercises.
    "synth"])
def test_chaos_reset_mid_stream_recovers(sched):
    """A seeded mid-stream link reset on each new schedule: pyrobust
    re-rendezvouses and the job finishes bit-exact."""
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust", "RABIT_SCHED": sched,
                    "RABIT_BACKOFF_BASE_MS": "10",
                    "RABIT_CHAOS": "5:reset@io=1.0*1;ranks=1"},
                   args=["1000", "3"],
                   tracker_groups="0,0,1,1") == 0


@pytest.mark.recovery
def test_kill_point_replay_on_halving():
    # rank 1 dies at version 1 seq 0 (the fused bucket op) with the
    # whole async stream riding halving/doubling; its restart must be
    # served the cached fused payload and split it back bit-exact.
    assert _launch("async_kill", 4,
                   {"RABIT_ENGINE": "pyrobust", "RABIT_SCHED": "halving",
                    "RABIT_MOCK": "1,1,0,0"}) == 0


# Tier-1 budget: the single-death replay above is the fast
# representative; the two-death variant rides `-m slow` (the recovery
# suite's own two-death matrix keeps the protocol shape covered).
@pytest.mark.recovery
@pytest.mark.slow
def test_kill_point_replay_on_halving_two_deaths():
    assert _launch("async_kill", 4,
                   {"RABIT_ENGINE": "pyrobust", "RABIT_SCHED": "halving",
                    "RABIT_MOCK": "2,1,0,0;1,2,1,0"}) == 0


# ------------------------------------------------- tuner round trip
@pytest.mark.slow
def test_tuner_round_trip_gate(tmp_path):
    """bench → cache → auto picks the measured winner per point: run
    the collectives bench at two sizes with --tune-dir, then a worker
    under rabit_sched=auto whose obs counters must show the cached
    winner carrying the traffic at a benchmarked point."""
    from rabit_tpu.sched import TuningCache
    from rabit_tpu.tracker.launch_local import launch

    tune = tmp_path / "tune"
    out = tmp_path / "collectives.json"
    code = launch(4, [sys.executable, "-m",
                      "rabit_tpu.tools.collectives_bench", str(out),
                      "--sizes", "16KB,256KB",
                      "--tune-dir", str(tune)],
                  extra_env={"RABIT_ENGINE": "pysocket"})
    assert code == 0
    cache = TuningCache.load(str(tune))
    assert cache is not None
    data = json.loads(out.read_text())
    assert data["schema"] >= 2 and data["world"] == 4 and data["host"]
    for size in ("16384", "262144"):
        winner = cache.pick("allreduce", int(size), 4)
        assert winner in data["sizes"][size], (winner, size)
        # the cached winner is the measured argmax among schedules
        rows = {k: v for k, v in data["sizes"][size].items()
                if k in data["schedules"]}
        assert winner == max(rows, key=rows.get)
    assert _launch("sched_auto_pick", 4,
                   {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "auto",
                    "RABIT_TUNE_DIR": str(tune), "RABIT_OBS": "1"}) == 0
