"""Seeded randomized fault-injection soak, wired into the suite.

A slow-marked gate over the protocol: a world-8 job with a seeded random
kill-point matrix per round, catching recovery interleavings the fixed
matrix in test_recovery.py misses (reference analogue: the die-hard
spirit of test/test.mk:7-24).  Run explicitly with ``pytest -m slow``.
On failure the soak tool prints the kill matrix so the scenario is
reproducible via ``python -m rabit_tpu.tools.soak --seed ...``.
"""
import pytest

pytestmark = pytest.mark.recovery


@pytest.mark.slow
def test_soak_seeded(native_lib):
    from rabit_tpu.tools import soak

    rc = soak.main(["--world", "8", "--rounds", "3", "--seed", "1234"])
    assert rc == 0, "soak failed — kill matrix printed above"


@pytest.mark.slow
def test_soak_seeded_pyrobust():
    """The same randomized die-hard/die-same soak through the pure-
    Python recovery path — no native library required."""
    from rabit_tpu.tools import soak

    rc = soak.main(["--world", "8", "--rounds", "2", "--seed", "1234",
                    "--engine", "pyrobust"])
    assert rc == 0, "pyrobust soak failed — kill matrix printed above"
