"""MPI engine: execute the engine body for real.

mpi4py is not bundled in the TPU image, so CI injects the test-only stub
runtime (tests/mpistub — COMM_WORLD over TCP) via PYTHONPATH; with a real
mpi4py installed the same worker runs unchanged under mpirun
(reference analogue: src/engine_mpi.cc:126-137; test/Makefile:27-37
builds speed_test.mpi against librabit_mpi the same way).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "tests", "mpistub")
WORKER = os.path.join(REPO, "tests", "workers", "check_mpi.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stub_env(rank: int, size: int, port: int) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = STUB + os.pathsep + env.get("PYTHONPATH", "")
    env["MPI_STUB_RANK"] = str(rank)
    env["MPI_STUB_SIZE"] = str(size)
    env["MPI_STUB_PORT"] = str(port)
    # no tracker in an MPI job
    env.pop("RABIT_TRACKER_URI", None)
    env.pop("RABIT_TRACKER_PORT", None)
    return env


@pytest.mark.parametrize("world", [2, 3])
def test_mpi_engine_stub(world):
    port = _free_port()
    procs = [subprocess.Popen([sys.executable, WORKER],
                              env=_stub_env(r, world, port), cwd=REPO)
             for r in range(world)]
    codes = [p.wait(timeout=120) for p in procs]
    assert codes == [0] * world, codes


def test_mpi_engine_real_mpi4py():
    """Skip-gated: runs only where a real mpi4py + mpirun exist."""
    from rabit_tpu.engine.mpi import mpi_available

    if not mpi_available() or os.environ.get("MPI_STUB_RANK"):
        pytest.skip("real mpi4py not installed")
    import shutil

    mpirun = shutil.which("mpirun")
    if mpirun is None:
        pytest.skip("mpirun not on PATH")
    proc = subprocess.run([mpirun, "-n", "2", sys.executable, WORKER],
                          cwd=REPO, timeout=120)
    assert proc.returncode == 0
