"""MPI engine: execute the engine body for real.

mpi4py is not bundled in the TPU image, so CI injects the test-only stub
runtime (tests/mpistub — COMM_WORLD over TCP) via PYTHONPATH; with a real
mpi4py installed the same worker runs unchanged under mpirun
(reference analogue: src/engine_mpi.cc:126-137; test/Makefile:27-37
builds speed_test.mpi against librabit_mpi the same way).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "tests", "mpistub")
WORKER = os.path.join(REPO, "tests", "workers", "check_mpi.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stub_env(rank: int, size: int, port: int) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = STUB + os.pathsep + env.get("PYTHONPATH", "")
    env["MPI_STUB_RANK"] = str(rank)
    env["MPI_STUB_SIZE"] = str(size)
    env["MPI_STUB_PORT"] = str(port)
    # no tracker in an MPI job
    env.pop("RABIT_TRACKER_URI", None)
    env.pop("RABIT_TRACKER_PORT", None)
    return env


@pytest.mark.parametrize("world", [2, 3])
def test_mpi_engine_stub(world):
    port = _free_port()
    procs = [subprocess.Popen([sys.executable, WORKER],
                              env=_stub_env(r, world, port), cwd=REPO)
             for r in range(world)]
    codes = [p.wait(timeout=120) for p in procs]
    assert codes == [0] * world, codes


def _real_mpirun() -> str | None:
    """The rebuilt launcher over the system OpenMPI runtime (the image
    ships libmpi/liborte but no openmpi-bin; rabit_tpu/native/mpi
    rebuilds the missing front-ends).  Falls back to a system mpirun;
    None when there is no MPI runtime at all."""
    from rabit_tpu.tools.speed_runner import ensure_mpi_tools

    mpirun = ensure_mpi_tools()
    if mpirun is not None and os.path.exists(mpirun):
        return mpirun
    import shutil

    return shutil.which("mpirun")


@pytest.mark.parametrize("world", [2, 4])
def test_mpi_engine_real_libmpi(world):
    """The engine body over the REAL system MPI under a real mpirun:
    multi-process MPI_Allreduce/Bcast/Allgather through the libmpi
    ctypes binding (reference analogue: src/engine_mpi.cc:126-137 run
    via test/Makefile's speed_test.mpi leg)."""
    mpirun = _real_mpirun()
    if mpirun is None:
        pytest.skip("no MPI runtime on this image")
    env = dict(os.environ)
    env.pop("RABIT_TRACKER_URI", None)
    env.pop("RABIT_TRACKER_PORT", None)
    # loopback-friendly transports; keep CI deterministic
    env.setdefault("OMPI_MCA_btl", "self,vader,tcp")
    proc = subprocess.run(
        [mpirun, "-n", str(world), "--oversubscribe", sys.executable,
         WORKER], cwd=REPO, timeout=180, env=env)
    assert proc.returncode == 0


def test_mpi_allreduce_baseline_tool():
    """The raw MPI_Allreduce baseline harness runs and reports bus
    bandwidth (the number BASELINE.md's >=90% target is quoted
    against; reference: test/speed_runner.py:13-18)."""
    mpirun = _real_mpirun()
    if mpirun is None:
        pytest.skip("no MPI runtime on this image")
    from rabit_tpu.tools.speed_runner import MPI_DIR

    env = dict(os.environ)
    env.setdefault("OMPI_MCA_btl", "self,vader,tcp")
    proc = subprocess.run(
        [mpirun, "-n", "2", "--oversubscribe",
         os.path.join(MPI_DIR, "mpi_speed"), "4096"],
        cwd=REPO, timeout=180, env=env, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "busbw_MBps=" in proc.stdout, proc.stdout
