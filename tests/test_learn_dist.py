"""Distributed learn-layer tests: kmeans and linear over real multi-process
jobs (tracker + socket engine), checked against single-process oracles.

Mirrors how the reference exercises its apps through the demo launcher
(reference: rabit-learn/kmeans run scripts, test/test.mk) with numeric
self-verification in the workers.
"""
import sys

import numpy as np
import pytest


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for row, label in zip(X, y):
            items = " ".join(
                f"{j}:{v:g}" for j, v in enumerate(row) if v != 0.0)
            f.write(f"{label:g} {items}\n")


def _shard_files(tmp_path, X, y, world):
    for r in range(world):
        _write_libsvm(tmp_path / f"part{r}.libsvm", X[r::world], y[r::world])
    _write_libsvm(tmp_path / "full.libsvm", X, y)
    return str(tmp_path / "part%d.libsvm"), str(tmp_path / "full.libsvm")


def _blobs(n=240, d=8, k=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.zeros((k, d), np.float32)
    centers[np.arange(k), np.arange(k)] = 4.0
    X = np.concatenate(
        [centers[i] + 0.1 * rng.standard_normal((n // k + 1, d))
         for i in range(k)])[:n].astype(np.float32)
    rng.shuffle(X)
    return X


@pytest.mark.parametrize("engine", ["pysocket", "native"])
def test_kmeans_distributed(tmp_path, engine, native_lib):
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    X = _blobs()
    pattern, full = _shard_files(tmp_path, X, np.zeros(len(X)), world)
    out = str(tmp_path / "cent")
    code = launch(world, [sys.executable, "tests/workers/kmeans_dist.py",
                          pattern, full, "3", "5", out],
                  extra_env={"RABIT_ENGINE": engine})
    assert code == 0
    cent = np.load(out + ".npy")
    assert cent.shape == (3, 8)
    # blobs are axis-aligned: each centroid should be dominated by one axis
    cn = cent / np.linalg.norm(cent, axis=1, keepdims=True)
    axes = sorted(np.argmax(cn, axis=1))
    assert axes == [0, 1, 2]


def test_kmeans_app_on_xla_engine(tmp_path):
    """kmeans.run over the XLA engine: the stats allreduce rides the
    device data plane (jax.Array through the engine), the checkpoint
    the control plane."""
    from rabit_tpu.tracker.launch_local import launch

    world = 2
    X = _blobs()
    pattern, _full = _shard_files(tmp_path, X, np.zeros(len(X)), world)
    out = str(tmp_path / "cent_xla")
    code = launch(world, [sys.executable,
                          "tests/workers/kmeans_run_xla.py",
                          pattern, "3", "5", out])
    assert code == 0
    cent = np.load(out + ".npy")
    cn = cent / np.linalg.norm(cent, axis=1, keepdims=True)
    assert sorted(np.argmax(cn, axis=1)) == [0, 1, 2]


def test_kmeans_app_on_xla_engine_death_reform(tmp_path, native_lib):
    """kmeans.run over the XLA engine with a mid-run death: the relaunch
    resumes from the checkpoint, the device plane re-forms at the next
    checkpoint boundary, and kmeans re-uploads its device shard (epoch
    change) — final centroids still agree across all ranks."""
    from rabit_tpu.tracker.launch_local import launch

    world = 3
    X = _blobs()
    pattern, _full = _shard_files(tmp_path, X, np.zeros(len(X)), world)
    out = str(tmp_path / "cent_xla_reform")
    code = launch(world, [sys.executable,
                          "tests/workers/kmeans_run_xla.py",
                          pattern, "3", "5", out],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_KMEANS_DIE": "1:2"},
                  watchdog_sec=20)
    assert code == 0
    cent = np.load(out + ".npy")
    cn = cent / np.linalg.norm(cent, axis=1, keepdims=True)
    assert sorted(np.argmax(cn, axis=1)) == [0, 1, 2]


def test_kmeans_distributed_with_faults(tmp_path, native_lib):
    """kmeans keeps its numeric guarantees across a mid-iteration death
    (the app-level version of the reference's model_recover matrix)."""
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    X = _blobs()
    pattern, full = _shard_files(tmp_path, X, np.zeros(len(X)), world)
    out = str(tmp_path / "cent_fault")
    code = launch(world, [sys.executable, "tests/workers/kmeans_dist.py",
                          pattern, full, "3", "5", out],
                  extra_env={"RABIT_ENGINE": "mock",
                             "RABIT_MOCK": "1,1,0,0;2,3,0,0"})
    assert code == 0
    cent = np.load(out + ".npy")
    cn = cent / np.linalg.norm(cent, axis=1, keepdims=True)
    assert sorted(np.argmax(cn, axis=1)) == [0, 1, 2]


def test_linear_distributed_matches_single(tmp_path, native_lib):
    """Distributed logistic training must match full-data single-process
    training (shard gradients sum exactly to the full gradient)."""
    import rabit_tpu
    from rabit_tpu.learn import LinearModel, LinearObjFunction
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    rng = np.random.default_rng(7)
    n, d = 240, 10
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal(d)
    # noisy labels + real L2 keep the optimum well-conditioned so the
    # distributed and single-process trajectories stay numerically close
    y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(n)).astype(np.float32)
    pattern, full = _shard_files(tmp_path, X, y, world)

    out_model = str(tmp_path / "dist.model")
    code = launch(world, [sys.executable, "tests/workers/linear_dist.py",
                          pattern, "logistic", out_model,
                          "reg_L2=0.1", "max_lbfgs_iter=25"],
                  extra_env={"RABIT_ENGINE": "native"})
    assert code == 0

    # single-process oracle on the full data
    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    obj = LinearObjFunction()
    obj.load_data(full)
    obj.set_param("objective", "logistic")
    obj.set_param("reg_L2", "0.1")
    obj.set_param("max_lbfgs_iter", "25")
    obj.set_param("silent", "1")
    obj.set_param("row_block", "64")
    obj.set_param("model_out", str(tmp_path / "single.model"))
    obj.run()
    rabit_tpu.finalize()

    dist = LinearModel()
    dist.load(out_model)
    single = LinearModel()
    single.load(str(tmp_path / "single.model"))
    assert dist.num_feature == single.num_feature
    np.testing.assert_allclose(dist.weight, single.weight,
                               rtol=1e-3, atol=1e-3)


def test_linear_distributed_with_faults(tmp_path, native_lib):
    """L-BFGS under deaths: the solver checkpoints a (global, local)
    state pair every iteration (reference: lbfgs.h:119,192 — the
    local-model path the reference exercises via local_recover); two
    workers dying at different versions must replay/reload and still
    land on the single-process optimum."""
    import rabit_tpu
    from rabit_tpu.learn import LinearModel, LinearObjFunction
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    rng = np.random.default_rng(11)
    n, d = 240, 10
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal(d)
    y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(n)).astype(np.float32)
    pattern, full = _shard_files(tmp_path, X, y, world)

    out_model = str(tmp_path / "dist_fault.model")
    code = launch(world, [sys.executable, "tests/workers/linear_dist.py",
                          pattern, "logistic", out_model,
                          "reg_L2=0.1", "max_lbfgs_iter=25"],
                  extra_env={"RABIT_ENGINE": "mock",
                             "RABIT_MOCK": "1,2,0,0;3,5,1,0"})
    assert code == 0

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    obj = LinearObjFunction()
    obj.load_data(full)
    obj.set_param("objective", "logistic")
    obj.set_param("reg_L2", "0.1")
    obj.set_param("max_lbfgs_iter", "25")
    obj.set_param("silent", "1")
    obj.set_param("row_block", "64")
    obj.set_param("model_out", str(tmp_path / "single_fault.model"))
    obj.run()
    rabit_tpu.finalize()

    dist = LinearModel()
    dist.load(out_model)
    single = LinearModel()
    single.load(str(tmp_path / "single_fault.model"))
    np.testing.assert_allclose(dist.weight, single.weight,
                               rtol=1e-3, atol=1e-3)
