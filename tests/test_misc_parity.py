"""Small parity pieces: splitrows tool, MPI engine gating."""
import numpy as np
import pytest

from rabit_tpu.learn.splitrows import split


def test_splitrows_partitions_all_rows(tmp_path):
    src = tmp_path / "data.libsvm"
    lines = [f"{i % 2} {i % 7}:{i}.0\n" for i in range(100)]
    src.write_text("".join(lines))
    names = split(str(src), str(tmp_path / "out"), 4)
    assert len(names) == 4
    got = []
    for n in names:
        with open(n) as f:
            got.extend(f.readlines())
    assert sorted(got) == sorted(lines)
    # deterministic seed: same split on a second run
    names2 = split(str(src), str(tmp_path / "again"), 4)
    for a, b in zip(names, names2):
        assert open(a).read() == open(b).read()


def test_allreduce_custom_world1():
    import rabit_tpu

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    ran = []
    a = np.arange(4, dtype=np.float32)
    out = rabit_tpu.allreduce_custom(
        a, lambda d, s: None, prepare_fun=lambda: ran.append(1))
    assert ran and (out == a).all()
    rabit_tpu.finalize()


@pytest.mark.parametrize("engine", ["pysocket", "native"])
def test_allreduce_custom_multiworker(engine, native_lib):
    import sys

    from rabit_tpu.tracker.launch_local import launch

    code = launch(3, [sys.executable,
                      "tests/workers/custom_reduce_py.py"],
                  extra_env={"RABIT_ENGINE": engine})
    assert code == 0


def test_mpi_engine_gated():
    from rabit_tpu.engine.mpi import mpi_available

    if mpi_available():
        pytest.skip("mpi4py present; gating not exercised")
    import rabit_tpu

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    with pytest.raises(Exception, match="mpi4py"):
        rabit_tpu.init(rabit_engine="mpi")
