"""Small parity pieces: splitrows tool, MPI engine gating."""
import numpy as np
import pytest

from rabit_tpu.learn.splitrows import split


def test_splitrows_partitions_all_rows(tmp_path):
    src = tmp_path / "data.libsvm"
    lines = [f"{i % 2} {i % 7}:{i}.0\n" for i in range(100)]
    src.write_text("".join(lines))
    names = split(str(src), str(tmp_path / "out"), 4)
    assert len(names) == 4
    got = []
    for n in names:
        with open(n) as f:
            got.extend(f.readlines())
    assert sorted(got) == sorted(lines)
    # deterministic seed: same split on a second run
    names2 = split(str(src), str(tmp_path / "again"), 4)
    for a, b in zip(names, names2):
        assert open(a).read() == open(b).read()


def test_mpi_engine_gated():
    from rabit_tpu.engine.mpi import mpi_available

    if mpi_available():
        pytest.skip("mpi4py present; gating not exercised")
    import rabit_tpu

    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    with pytest.raises(Exception, match="mpi4py"):
        rabit_tpu.init(rabit_engine="mpi")
