"""Durable checkpoint tier + supervised cold restart.

The matrix behind doc/fault_tolerance.md "Durable checkpoints &
heartbeats": store-level durability invariants (atomic persists, CRC
fallback, torn-write tolerance, retention), the kill-ALL-ranks cold
restart resuming bit-exact from disk, the heartbeat sweep evicting a
hung rank without a collective op touching it, the typed version-skew
guard, and writer-death-during-persist never tearing a manifest.

Run with ``pytest -m ckpt``; the randomized big brother is
``python -m rabit_tpu.tools.soak --cold-restart`` (slow-marked gate at
the bottom).
"""
import json
import os
import sys
import time

import pytest

from rabit_tpu.ckpt import (CheckpointSkewError, CheckpointStore,
                            expand_dir, pack_blob, unpack_blob)

pytestmark = pytest.mark.ckpt


# ------------------------------------------------------------ store units
def test_store_roundtrip_and_retention(tmp_path):
    """Persist/load round-trip incl. local blobs; retention keeps only
    the rabit_ckpt_keep newest versions (manifest and blob files)."""
    s = CheckpointStore(str(tmp_path), rank=0, keep=3)
    for v in range(1, 6):
        s.persist(v, 4, b"G%d" % v, {0: b"L0", 3: b"L3%d" % v})
    dc = s.load_latest()
    assert (dc.version, dc.world, dc.writer) == (5, 4, 0)
    assert dc.global_blob == b"G5"
    assert dc.locals == {0: b"L0", 3: b"L35"}
    blobs = [f for f in os.listdir(tmp_path) if f.endswith(".ckpt")]
    assert sorted(blobs) == ["v00000003.r0.ckpt", "v00000004.r0.ckpt",
                             "v00000005.r0.ckpt"]
    assert unpack_blob(dc.raw).version == 5  # raw re-serves verbatim


def test_store_corrupt_and_truncated_fall_back(tmp_path):
    """A corrupt newest blob fails CRC and the loader silently falls
    back version by version; invalid blobs never count as newest (the
    skew-guard input)."""
    s = CheckpointStore(str(tmp_path), rank=0, keep=5)
    for v in (1, 2, 3):
        s.persist(v, 2, b"G%d" % v)
    p3 = tmp_path / "v00000003.r0.ckpt"
    raw = bytearray(p3.read_bytes())
    raw[len(raw) // 2] ^= 0xFF            # bit-flip -> CRC mismatch
    p3.write_bytes(bytes(raw))
    assert s.load_latest().version == 2
    assert s.newest_version() == 2
    p2 = tmp_path / "v00000002.r0.ckpt"
    p2.write_bytes(p2.read_bytes()[:11])  # truncation
    assert s.load_latest().version == 1
    scan = {e["version"]: e["valid"] for e in s.scan()}
    assert scan == {3: False, 2: False, 1: True}


def test_store_torn_writes_are_invisible(tmp_path):
    """Crash shapes a dying writer can leave behind — a stray tmp file,
    a missing manifest, a manifest naming a deleted blob — must never
    confuse the loader."""
    s = CheckpointStore(str(tmp_path), rank=1, keep=3)
    s.persist(1, 2, b"G1")
    s.persist(2, 2, b"G2")
    (tmp_path / ".v00000009.r1.ckpt.tmp.1234").write_bytes(b"torn garbage")
    assert s.load_latest().version == 2
    # manifest gone (crash between blob and manifest): orphan scan wins
    os.remove(tmp_path / s.manifest_name)
    assert s.load_latest().version == 2
    # manifest naming a vanished blob: skipped, older one serves
    s.persist(3, 2, b"G3")
    os.remove(tmp_path / "v00000003.r1.ckpt")
    assert s.load_latest().version == 2


def test_store_multi_writer_shared_dir(tmp_path):
    """Writers on a shared filesystem never race: each owns its own
    manifest, and the loader takes the max valid version across all."""
    CheckpointStore(str(tmp_path), rank=0).persist(4, 4, b"w0")
    CheckpointStore(str(tmp_path), rank=2).persist(6, 4, b"w2")
    dc = CheckpointStore(str(tmp_path), rank=0).load_latest()
    assert (dc.version, dc.writer, dc.global_blob) == (6, 2, b"w2")


def test_skew_error_and_dir_expansion():
    e = CheckpointSkewError(9, 2)
    assert e.disk_version == 9 and e.agreed_version == 2
    assert "9" in str(e) and "2" in str(e)
    assert expand_dir("/disks/{rank}/ckpt", 3) == "/disks/3/ckpt"
    with pytest.raises(ValueError):
        unpack_blob(pack_blob(1, 2, 0, b"x")[:-1] + b"\x00")


# ------------------------------------------------- cold restart (headline)
def test_cold_restart_all_ranks_killed_bitexact(tmp_path):
    """The headline gate: every rank SIGKILLs itself right after
    committing version 2 — no in-memory replica survives anywhere — the
    supervisor relaunches the world, the relaunched lives resume at the
    last durably committed version (asserted inside the worker: never
    0), and the final model is bit-identical to an uninterrupted run."""
    from rabit_tpu.tracker.launch_local import launch

    world, ndata, niter = 4, 400, 4
    ref = tmp_path / "ref"
    code = launch(world, [sys.executable, "tests/workers/cold_restart.py",
                          str(ndata), str(niter)],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_OUT_DIR": str(ref)})
    assert code == 0
    cold = tmp_path / "cold"
    cold.mkdir()
    out = tmp_path / "out"
    code = launch(world, [sys.executable, "tests/workers/cold_restart.py",
                          str(ndata), str(niter)],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_OUT_DIR": str(out),
                             "RABIT_COLD_DIR": str(cold),
                             "RABIT_COLD_KILL_ITER": "2"},
                  ckpt_dir=str(tmp_path / "ckpt"), heartbeat_sec=0.5,
                  max_restarts=3, restart_backoff_ms=50)
    assert code == 0
    assert len(list(cold.glob("killed.*"))) == world  # everyone died
    for r in range(world):
        assert (ref / f"final.{r}").read_bytes() == \
            (out / f"final.{r}").read_bytes(), \
            f"rank {r} final model not bit-identical after cold restart"


def test_cold_restart_corrupt_newest_falls_back(tmp_path):
    """CRC-corrupt + truncated newest blobs on EVERY writer: a fresh
    cold start must resume from the next-older valid version (asserted
    via RABIT_EXPECT_START_VERSION inside the worker)."""
    from rabit_tpu.tracker.launch_local import launch

    ckpt = tmp_path / "ckpt"
    code = launch(2, [sys.executable, "tests/workers/cold_restart.py",
                      "300", "3"],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_CKPT_KEEP": "4"},
                  ckpt_dir=str(ckpt))
    assert code == 0
    v3 = sorted(ckpt.glob("v00000003.*.ckpt"))
    assert v3, sorted(os.listdir(ckpt))
    raw = bytearray(v3[0].read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    v3[0].write_bytes(bytes(raw))          # writer 0: CRC corruption
    for p in v3[1:]:
        p.write_bytes(p.read_bytes()[:9])  # other writers: truncation
    code = launch(2, [sys.executable, "tests/workers/cold_restart.py",
                      "300", "3"],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_CKPT_KEEP": "4",
                             "RABIT_EXPECT_START_VERSION": "2"},
                  ckpt_dir=str(ckpt))
    assert code == 0


def test_writer_death_during_persist_leaves_no_torn_state(tmp_path):
    """Rank 0 dies between the v2 blob rename and the manifest rename
    (the RABIT_CKPT_CRASH seam).  The job must complete via the normal
    kill-point restart, and afterwards every manifest must parse and
    every blob any manifest names must validate — atomic renames mean a
    writer death can cost at most one version of durability, never a
    torn store."""
    from rabit_tpu.tracker.launch_local import launch

    ckpt = tmp_path / "ckpt"
    code = launch(4, [sys.executable, "tests/workers/model_recover.py",
                      "500", "3"],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_CKPT_CRASH": "0,2",
                             "RABIT_CKPT_KEEP": "8"},
                  ckpt_dir=str(ckpt))
    assert code == 0
    store = CheckpointStore(str(ckpt), rank=0)
    assert store.load_latest().version == 3
    assert all(e["valid"] for e in store.scan()), store.scan()
    # the torn persist left rank 0's v2 blob orphaned but intact, and
    # no manifest ever named it in a half-written state
    for m in ckpt.glob("manifest*.json"):
        json.loads(m.read_text())  # parses, or the store is torn


# ------------------------------------------------------ version-skew guard
def test_relaunched_rank_with_newer_disk_raises_skew(tmp_path):
    """A relaunched rank whose durable tier holds a NEWER valid version
    than the cluster agreed must raise the typed CheckpointSkewError
    (verified inside the worker, surfaced as exit code 42) instead of
    silently serving stale state."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(3, [sys.executable, "tests/workers/ckpt_skew.py"],
                  extra_env={"RABIT_ENGINE": "pyrobust",
                             "RABIT_MOCK": "1,1,1,0"},
                  ckpt_dir=str(tmp_path / "ckpt"))
    assert code == 42, code


# ------------------------------------------------- heartbeat failure path
def test_heartbeat_evicts_stalled_rank_without_collective(tmp_path):
    """A SIGSTOP'd (hung-but-connected) rank with the DEFAULT 600 s
    link timeout: only the tracker's heartbeat sweep can notice it
    inside the miss budget — no collective op ever errors on its own.
    The sweep's dead verdict must kill+relaunch the rank and the job
    must finish orders of magnitude under the link timeout.  The
    liveness transitions and the relaunched rank's re-registration land
    merged (not duplicated) in the tracker obs report."""
    import io

    from rabit_tpu.tools.obs_report import render_report
    from rabit_tpu.tracker.launch_local import launch

    obs_dir = tmp_path / "obs"
    env = {"RABIT_ENGINE": "pyrobust", "RABIT_STALL_DIR": str(tmp_path)}
    t0 = time.monotonic()
    code = launch(4, [sys.executable, "tests/workers/stall_worker.py",
                      "500", "3"], extra_env=env, heartbeat_sec=0.3,
                  obs_dir=str(obs_dir))
    elapsed = time.monotonic() - t0
    assert code == 0
    assert (tmp_path / "stalled").exists()  # the stall really happened
    assert elapsed < 60, f"heartbeat eviction took {elapsed:.1f}s"
    report = json.loads((obs_dir / "obs_report.json").read_text())
    phases = [e["phase"] for e in report["recovery_timeline"]
              if e.get("name") == "liveness"]
    assert "alive" in phases and "dead" in phases, phases
    assert "relaunch" in phases, phases  # the restart event
    # same-rank re-registration merges (not duplicates) rank summaries
    assert report["ranks_reported"] == [0, 1, 2, 3]
    assert len(report["ranks"]) == 4
    out = io.StringIO()
    render_report(report, out=out)
    assert "liveness transitions" in out.getvalue()


# ---------------------------------------------------------- slow soak gate
@pytest.mark.slow
def test_soak_cold_restart_gate():
    """Randomized kill-all cold-restart rounds (seeded), bit-exact vs an
    uninterrupted reference — the durable tier's randomized big brother,
    mixed with wire chaos."""
    from rabit_tpu.tools import soak

    rc = soak.main(["--cold-restart", "--engine", "pyrobust", "--world",
                    "6", "--rounds", "2", "--niter", "5", "--seed", "99"])
    assert rc == 0, "cold-restart soak failed — repro line printed above"
    rc = soak.main(["--cold-restart", "--chaos", "--engine", "pyrobust",
                    "--world", "4", "--rounds", "1", "--niter", "4",
                    "--seed", "100"])
    assert rc == 0, "chaos cold-restart soak failed — repro printed above"
