"""Elastic membership + tracker HA tests.

Covers the ISSUE 6 contract (doc/fault_tolerance.md "Elastic
membership & tracker HA"):

* ``splitrows`` reshard math: the row shards are an exact partition of
  the dataset for *every* world size, so an elastic rescale (4→6→3)
  re-shards with no row dropped or duplicated, deterministically —
  and the in-memory stream agrees with the on-disk ``split()`` files;
* deterministic rescale rank reassignment (survivors by old rank,
  joiners by task_id, rank space compacted);
* the ``cmd=epoch`` membership poll (pending targets visible at
  checkpoint-commit boundaries);
* heartbeat-detected death → scale-*down* target, with the liveness
  event ordered causally BEFORE the epoch transition it triggers;
* tracker crash-restart mid-barrier and mid-epoch: the journal
  (``state_dir``, atomic CheckpointStore tier) replays the formation
  barrier round / the pending rescale target, and the workers'
  re-posts complete what the dead incarnation started;
* the slow grow/shrink soak gate (``tools/soak.py --elastic``).
"""
import socket
import time

import pytest

from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.tracker import Tracker

pytestmark = pytest.mark.elastic


# ------------------------------------------------------- reshard math
@pytest.mark.parametrize("n_rows", [1, 7, 101, 400])
def test_splitrows_exact_partition_4_6_3(n_rows):
    """Every row is assigned to exactly one rank at every world size of
    the 4→6→3 rescale history — the property elastic reshard
    correctness rests on (uneven worlds on purpose: 101 % 3 != 0)."""
    from rabit_tpu.learn.splitrows import rows_for_rank, shard_indices

    for k in (4, 6, 3):
        shards = shard_indices(n_rows, k)
        assert len(shards) == k
        flat = [i for shard in shards for i in shard]
        # exactly once: no row dropped, no row duplicated
        assert sorted(flat) == list(range(n_rows))
        # the per-rank view replays the very same assignment stream
        for rank in range(k):
            assert rows_for_rank(n_rows, rank, k) == shards[rank]


def test_splitrows_file_split_matches_stream(tmp_path):
    """``split()`` (on-disk shard files) and ``shard_indices`` (the
    in-memory reshard the elastic layer uses) consume the same
    assignment stream: file contents match row for row."""
    from rabit_tpu.learn.splitrows import shard_indices, split

    rows = [f"{i} 1:{i}\n" for i in range(57)]
    fin = tmp_path / "data.libsvm"
    fin.write_text("".join(rows))
    names = split(str(fin), str(tmp_path / "out"), 5)
    shards = shard_indices(57, 5)
    for k, name in enumerate(names):
        want = "".join(rows[i] for i in shards[k])
        assert open(name).read() == want


# -------------------------------------------- rescale rank assignment
def test_rescale_rank_assignment_deterministic():
    """Survivors keep their old-rank order (a pure scale-up moves
    nobody), joiners follow sorted by task_id, and the rank space
    compacts to exactly [0, world)."""
    from types import SimpleNamespace

    tr = Tracker.__new__(Tracker)  # no sockets needed
    # Scale-up 4->6: every member keeps its exact rank.
    tr._rank_of = {"a": 2, "b": 0, "c": 3, "d": 1}
    regs = [SimpleNamespace(task_id=t)
            for t in ("a", "b", "c", "d", "z-join", "y-join")]
    tr._assign_ranks_rescale(regs, 6)
    assert tr._rank_of == {"b": 0, "d": 1, "a": 2, "c": 3,
                           "y-join": 4, "z-join": 5}
    # Scale-down 6->3 with one join: survivors compact in old-rank
    # order, the joiner takes the last rank.
    tr._rank_of = {"a": 2, "b": 0, "c": 3, "d": 1}
    regs = [SimpleNamespace(task_id=t) for t in ("c", "a", "new")]
    tr._assign_ranks_rescale(regs, 3)
    assert tr._rank_of == {"a": 0, "c": 1, "new": 2}


# ------------------------------------------------- tracker wire tests
def _register(addr, task_id, cmd, port=12345):
    """Send one rendezvous registration; the caller recvs the reply
    once the round completes (the send never blocks, so rounds can be
    driven sequentially without threads)."""
    s = socket.create_connection(addr, timeout=30)
    P.send_u32(s, P.MAGIC)
    P.send_str(s, cmd)
    P.send_str(s, task_id)
    P.send_u32(s, 0)
    P.send_str(s, "127.0.0.1")
    P.send_u32(s, port)
    return s


def _round(addr, cmds: dict[str, str]) -> dict[str, P.TopologyReply]:
    socks = {t: _register(addr, t, c) for t, c in cmds.items()}
    out = {}
    for t, s in socks.items():
        out[t] = P.TopologyReply.recv(s)
        s.close()
    return out


def _epoch_poll(addr, task_id="poll", version=0):
    s = socket.create_connection(addr, timeout=30)
    try:
        P.send_u32(s, P.MAGIC)
        P.send_str(s, P.CMD_EPOCH)
        P.send_str(s, task_id)
        P.send_u32(s, 0)
        P.send_u32(s, version)
        return P.recv_u32(s), P.recv_u32(s), P.recv_u32(s)
    finally:
        s.close()


def _wait(pred, deadline_sec=10.0):
    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _journal_flushed(t: Tracker) -> bool:
    """The newest in-memory state made it to disk (journal writes
    happen on handler threads after the mutation is visible, so a
    'crash' right after observing the mutation can outrun the write)."""
    return (t._state_store.newest_version() or 0) >= t._state_seq


def test_epoch_poll_and_joiner_admission():
    """The cmd=epoch poll reports (epoch, target_epoch, target_world);
    a parked joiner flips the pending target, and the completed rescale
    round admits it with the epoch bumped and survivor ranks stable."""
    t = Tracker(2, max_workers=4)
    t.start()
    joiner = None
    try:
        addr = (t.host, t.port)
        r1 = _round(addr, {"a": P.CMD_START, "b": P.CMD_START})
        assert {r.world for r in r1.values()} == {2}
        assert {r.epoch for r in r1.values()} == {0}
        assert _epoch_poll(addr, version=3) == (0, 0, 2)
        assert t.committed_version == 3

        joiner = _register(addr, "c", P.CMD_START)
        assert _wait(lambda: _epoch_poll(addr)[1:] == (1, 3))
        # Members re-rendezvous with cmd=rescale at the commit
        # boundary; the parked joiner completes the round.
        socks = {tid: _register(addr, tid, P.CMD_RESCALE)
                 for tid in ("a", "b")}
        replies = {tid: P.TopologyReply.recv(s)
                   for tid, s in socks.items()}
        replies["c"] = P.TopologyReply.recv(joiner)
        for s in socks.values():
            s.close()
        assert {r.world for r in replies.values()} == {3}
        assert {r.epoch for r in replies.values()} == {1}
        # survivors keep their ranks; the joiner compacts onto the end
        assert {replies["a"].rank, replies["b"].rank} == \
               {r1["a"].rank, r1["b"].rank}
        assert replies["c"].rank == 2
        assert _epoch_poll(addr) == (1, 1, 3)
    finally:
        t.stop()
        if joiner is not None:
            joiner.close()


def test_heartbeat_death_scales_down_liveness_first():
    """An EOF'd heartbeat channel (SIGKILL shape) turns into a pending
    scale-down target — and the liveness 'lost' event lands in the
    timeline BEFORE the epoch transition it causes, so the obs report
    orders the scale-down causally."""
    t = Tracker(3, min_workers=2, heartbeat_miss=5.0)
    t.start()
    try:
        addr = (t.host, t.port)
        r1 = _round(addr, {"x": P.CMD_START, "y": P.CMD_START,
                           "z": P.CMD_START})
        hb = socket.create_connection(addr, timeout=30)
        P.send_u32(hb, P.MAGIC)
        P.send_str(hb, P.CMD_HEARTBEAT)
        P.send_str(hb, "z")
        P.send_u32(hb, 0)
        P.send_u32(hb, 50)  # period_ms
        P.send_u32(hb, 1)   # one beat
        hb.close()          # EOF without the bye == death
        assert _wait(lambda: _epoch_poll(addr)[1:] == (1, 2))
        evs = list(t._events)
        lost = next(i for i, e in enumerate(evs)
                    if e.get("name") == "liveness"
                    and e.get("phase") == "lost" and e.get("task") == "z")
        pend = next(i for i, e in enumerate(evs)
                    if e.get("name") == "epoch"
                    and e.get("phase") == "pending")
        assert lost < pend, evs
        # Survivors re-rendezvous: world 2, epoch 1, old ranks compact.
        r2 = _round(addr, {"x": P.CMD_RESCALE, "y": P.CMD_RESCALE})
        assert {r.world for r in r2.values()} == {2}
        assert {r.epoch for r in r2.values()} == {1}
        old = sorted(("x", "y"), key=lambda tid: r1[tid].rank)
        assert [r2[tid].rank for tid in old] == [0, 1]
    finally:
        t.stop()


def test_supervisor_note_dead_scales_down():
    """Elastic leave WITHOUT heartbeats armed: the launcher's
    ``note_dead`` (keepalive saw the process exit, budget spent) is the
    tracker's only death signal — it must set the pending scale-down
    target, with the liveness event ordered before the epoch move."""
    t = Tracker(3, min_workers=2)
    t.start()
    try:
        addr = (t.host, t.port)
        r1 = _round(addr, {"x": P.CMD_START, "y": P.CMD_START,
                           "z": P.CMD_START})
        t.note_dead("z")
        assert _wait(lambda: _epoch_poll(addr)[1:] == (1, 2))
        evs = list(t._events)
        lost = next(i for i, e in enumerate(evs)
                    if e.get("name") == "liveness"
                    and e.get("phase") == "lost" and e.get("task") == "z")
        pend = next(i for i, e in enumerate(evs)
                    if e.get("name") == "epoch"
                    and e.get("phase") == "pending")
        assert lost < pend, evs
        r2 = _round(addr, {"x": P.CMD_RESCALE, "y": P.CMD_RESCALE})
        assert {r.world for r in r2.values()} == {2}
        old = sorted(("x", "y"), key=lambda tid: r1[tid].rank)
        assert [r2[tid].rank for tid in old] == [0, 1]
    finally:
        t.stop()


def test_tracker_restart_mid_barrier(tmp_path):
    """A tracker crash while the formation barrier is half-posted must
    not lose the round: the restarted tracker replays the journal (who
    already arrived, the rank map) and the workers' re-posts complete
    the barrier."""
    t1 = Tracker(2, state_dir=str(tmp_path))
    t1.start()
    addr1 = (t1.host, t1.port)
    r1 = _round(addr1, {"0": P.CMD_START, "1": P.CMD_START})
    # "0" posts and parks; "1" has not arrived yet.
    post0 = socket.create_connection(addr1, timeout=30)
    P.send_u32(post0, P.MAGIC)
    P.send_str(post0, P.CMD_FORMBAR)
    P.send_str(post0, "0")
    P.send_u32(post0, 0)
    assert _wait(lambda: "0" in t1._formbar_posted
                 and _journal_flushed(t1))
    t1.stop()  # crash mid-barrier (parked socket dies with it)
    post0.close()

    t2 = Tracker(2, state_dir=str(tmp_path))
    try:
        # Journal replay: the half-posted barrier and the rank map
        # survived the crash.
        assert t2._formbar_posted == {"0"}
        assert t2._formbar_state == "open"
        assert t2._rank_of == {tid: r.rank for tid, r in r1.items()}
        t2.start()
        addr2 = (t2.host, t2.port)
        socks = []
        for tid in ("0", "1"):  # "0" re-posts after its socket died
            s = socket.create_connection(addr2, timeout=30)
            P.send_u32(s, P.MAGIC)
            P.send_str(s, P.CMD_FORMBAR)
            P.send_str(s, tid)
            P.send_u32(s, 0)
            socks.append(s)
        for s in socks:
            assert P.recv_u32(s) == 1  # barrier completed: proceed
            s.close()
    finally:
        t2.stop()


def test_tracker_restart_mid_epoch(tmp_path):
    """A tracker crash with a rescale epoch PENDING (joiner admitted,
    round not yet complete) must not lose the target: the restarted
    tracker replays membership + target_world and the re-registrations
    complete the grow with the epoch bumped."""
    t1 = Tracker(2, max_workers=4, state_dir=str(tmp_path))
    t1.start()
    addr1 = (t1.host, t1.port)
    r1 = _round(addr1, {"a": P.CMD_START, "b": P.CMD_START})
    joiner = _register(addr1, "c", P.CMD_START)
    assert _wait(lambda: _epoch_poll(addr1)[1:] == (1, 3)
                 and _journal_flushed(t1))
    t1.stop()  # crash mid-epoch (the parked joiner's socket dies)
    joiner.close()

    t2 = Tracker(2, max_workers=4, state_dir=str(tmp_path))
    try:
        assert t2._members == {"a", "b"}
        assert t2._target_world == 3
        assert t2.epoch == 0
        t2.start()
        addr2 = (t2.host, t2.port)
        # Everyone re-registers against the restarted tracker: the
        # members with cmd=rescale, the joiner retrying its start.
        r2 = _round(addr2, {"a": P.CMD_RESCALE, "b": P.CMD_RESCALE,
                            "c": P.CMD_START})
        assert {r.world for r in r2.values()} == {3}
        assert {r.epoch for r in r2.values()} == {1}
        assert {r2["a"].rank, r2["b"].rank} == \
               {r1["a"].rank, r1["b"].rank}
        assert r2["c"].rank == 2
    finally:
        t2.stop()


def test_tracker_restart_preserves_dead_verdicts(tmp_path):
    """A scale-down verdict must survive a tracker crash: the dead
    worker never reconnects to re-earn it, so a restart that forgot
    ``_dead_tasks`` would recompute the target from "everyone alive"
    and stall the rescale round on a corpse."""
    t1 = Tracker(3, min_workers=2, state_dir=str(tmp_path))
    t1.start()
    addr1 = (t1.host, t1.port)
    r1 = _round(addr1, {"x": P.CMD_START, "y": P.CMD_START,
                        "z": P.CMD_START})
    t1.note_dead("z")
    assert _wait(lambda: _epoch_poll(addr1)[1:] == (1, 2)
                 and _journal_flushed(t1))
    t1.stop()

    t2 = Tracker(3, min_workers=2, state_dir=str(tmp_path))
    try:
        assert t2._dead_tasks == {"z"}
        assert t2._target_world == 2
        t2.start()
        addr2 = (t2.host, t2.port)
        r2 = _round(addr2, {"x": P.CMD_RESCALE, "y": P.CMD_RESCALE})
        assert {r.world for r in r2.values()} == {2}
        assert {r.epoch for r in r2.values()} == {1}
        old = sorted(("x", "y"), key=lambda tid: r1[tid].rank)
        assert [r2[tid].rank for tid in old] == [0, 1]
    finally:
        t2.stop()


# ------------------------------------------------------- typed errors
def test_world_changed_error_contract():
    """The typed errors ride the top-level API (RecoveryError /
    CheckpointSkewError precedent) and WorldChangedError carries the
    coordinates the resume path needs."""
    import rabit_tpu
    from rabit_tpu.engine.pysocket import LinkError

    e = rabit_tpu.WorldChangedError(4, 6, 2)
    assert (e.old_world, e.new_world, e.epoch) == (4, 6, 2)
    assert isinstance(e, rabit_tpu.RabitError)
    assert issubclass(rabit_tpu.TrackerLostError, LinkError)
    assert "WorldChangedError" in rabit_tpu.__all__
    assert "TrackerLostError" in rabit_tpu.__all__


# ----------------------------------------------------- the slow gate
@pytest.mark.slow
def test_soak_elastic():
    """The headline gate: world 4->6->3 at commit boundaries with a
    seeded tracker kill+restart mixed in; every rescale segment
    bit-identical to a fresh fixed-world job resumed from the same
    committed blob (see tools/soak.py --elastic)."""
    from rabit_tpu.tools import soak

    rc = soak.main(["--elastic", "--rounds", "1", "--seed", "1234"])
    assert rc == 0, "elastic soak failed — scenario printed above"
