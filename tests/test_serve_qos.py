"""QoS-classed serving front end tests (ISSUE 20, doc/serving.md).

Covers the tail-tolerance contract piece by piece:

* v2 predict frames (qos + idempotency key) with feature negotiation —
  a default-constructed request stays BYTE-IDENTICAL to the v1 frame,
  so pre-QoS clients and servers interoperate unchanged;
* per-class admission budgets and the lower-class eviction policy
  (bronze sheds first, gold last, a class never displaces itself),
  deterministic like the rest of the shed policy;
* the bounded idempotency cache: a seeded property test replays hedge
  interleavings (hedge-before-serve, hedge-after-commit, hedge-after-
  dedup-eviction) against a naive unbounded model — exactly one serve
  per unevicted key, and the eviction re-serve is the documented
  degradation, never a silent one;
* a standalone rank answering a replayed idempotency key with the
  typed Duplicate carrying the bitwise-identical cached answer;
* the straggler-aware router's conviction hysteresis and smooth-WRR
  traffic shift (same knobs as obs/adapt.py);
* per-class books on the obs plane: the LiveTable qos fold, the
  serving-plane straggler scores, the labeled
  ``rabit_serve_qos_requests_total{qos,status}`` exposition and the
  straggler-score max-merge;
* the postmortem serving-books fold (per-class balance verdicts,
  hedge/duplicate counts);
* the supervisor/client CLI seams (``--qos-budgets``,
  ``--slow-task-ms``, qos-mix parsing);
* the slow full gate: ``tools/soak.py --qos``.
"""
import json
import socket
import struct

import numpy as np
import pytest

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu import serve as S
from rabit_tpu.serve import dedup as dedup_mod
from rabit_tpu.serve import protocol as SP
from rabit_tpu.serve.batching import AdmissionGate, QueuedRequest
from rabit_tpu.utils.serial import serialize_model

pytestmark = [pytest.mark.serve, pytest.mark.serve_qos]


# ------------------------------------------------------------- helpers
def _make_store(path, versions=(1,), dim=8, seed=0):
    store = ckpt_mod.CheckpointStore(str(path), rank=0)
    weights = {}
    rng = np.random.default_rng(seed)
    for v in versions:
        w = rng.standard_normal(dim)
        store.persist(v, 1, serialize_model({"w": w}))
        weights[v] = w
    return store, weights


def _start_rank(model_dir, **kw):
    kw.setdefault("batch_wait_ms", 2)
    rank = S.ServeRank(str(model_dir), **kw)
    rank.start()
    return rank


def _qreq(i, qos=SP.QOS_SILVER, arrival=0.0, deadline=None):
    return QueuedRequest(req_id=i, features=np.zeros(1, np.float32),
                         arrival=arrival, deadline=deadline, qos=qos)


# ------------------------------------------------------- wire protocol
def test_default_request_is_byte_identical_v1():
    """Feature negotiation is BY FRAME: a request with default qos and
    no idempotency key emits exactly the v1 bytes, so an unupgraded
    server (or a byte-level golden test) never sees v2."""
    x = np.arange(3, dtype=np.float32)
    req = SP.PredictRequest(42, 250, x)
    golden = struct.pack("<IIII", SP.MAGIC_PREDICT, 42, 250,
                         3) + x.tobytes()
    assert req.encode() == golden


def test_v2_round_trip_qos_and_idem_key():
    a, b = socket.socketpair()
    try:
        import rabit_tpu.tracker.protocol as P

        x = np.arange(4, dtype=np.float32)
        SP.PredictRequest(7, 99, x, qos=SP.QOS_GOLD,
                          idem_key=0xDEADBEEFCAFE).send(a)
        assert P.recv_u32(b) == SP.MAGIC_PREDICT2
        req = SP.PredictRequest.recv_tail2(b)
        assert (req.req_id, req.qos, req.deadline_ms, req.idem_key) \
            == (7, SP.QOS_GOLD, 99, 0xDEADBEEFCAFE)
        assert req.qos_name == "gold"
        np.testing.assert_array_equal(req.features, x)

        # A non-default qos alone (no key) also selects the v2 frame.
        SP.PredictRequest(8, 0, x, qos=SP.QOS_BRONZE).send(a)
        assert P.recv_u32(b) == SP.MAGIC_PREDICT2
        assert SP.PredictRequest.recv_tail2(b).qos == SP.QOS_BRONZE
    finally:
        a.close()
        b.close()


def test_v2_unknown_qos_clamps_down_not_up():
    """A stray client cannot buy priority with a garbage class: an
    unknown qos value decodes as bronze."""
    a, b = socket.socketpair()
    try:
        import rabit_tpu.tracker.protocol as P

        x = np.zeros(1, np.float32)
        a.sendall(struct.pack("<IIIIQI", SP.MAGIC_PREDICT2, 1, 999,
                              0, 5, 1) + x.tobytes())
        P.recv_u32(b)
        assert SP.PredictRequest.recv_tail2(b).qos == SP.QOS_BRONZE
    finally:
        a.close()
        b.close()


# --------------------------------------------- per-class admission
def test_class_budget_sheds_within_class():
    """A class that spent its own budget sheds within-class — it never
    evicts anyone (a class cannot displace itself), and other classes
    keep their room."""
    gate = AdmissionGate(queue_max=8, batch_max=2, batch_wait_ms=1000,
                         qos_budgets={SP.QOS_BRONZE: 2})
    assert gate.submit(_qreq(0, SP.QOS_BRONZE))[0] == "admitted"
    assert gate.submit(_qreq(1, SP.QOS_BRONZE))[0] == "admitted"
    verdict, retry = gate.submit(_qreq(2, SP.QOS_BRONZE))
    assert verdict == "shed_queue_full" and retry >= 1
    assert gate.pop_evicted() == []
    assert gate.submit(_qreq(3, SP.QOS_SILVER))[0] == "admitted"
    pc = gate.stats.per_class
    assert pc["bronze"] == {"offered": 3, "admitted": 2,
                            "shed_queue_full": 1, "shed_deadline": 0,
                            "shed_evicted": 0, "timed_out": 0}
    assert pc["silver"]["admitted"] == 1


def test_eviction_lowest_class_first_newest_within():
    """At a FULL queue a higher-class arrival evicts the newest member
    of the LOWEST strictly-lower class present — bronze goes before
    silver even when silver arrived later."""
    gate = AdmissionGate(queue_max=3, batch_max=2, batch_wait_ms=1000)
    assert gate.submit(_qreq(0, SP.QOS_BRONZE, arrival=0.0))[0] \
        == "admitted"
    assert gate.submit(_qreq(1, SP.QOS_BRONZE, arrival=1.0))[0] \
        == "admitted"
    assert gate.submit(_qreq(2, SP.QOS_SILVER, arrival=2.0))[0] \
        == "admitted"
    verdict, _ = gate.submit(_qreq(3, SP.QOS_GOLD, arrival=3.0))
    assert verdict == "admitted"
    victims = gate.pop_evicted()
    assert [v.req_id for v in victims] == [1]     # newest BRONZE
    assert victims[0].shed == "evicted"
    assert gate.pop_evicted() == []               # drained exactly once
    assert gate.stats.shed_evicted == 1
    assert gate.stats.per_class["bronze"]["shed_evicted"] == 1
    assert gate.depth() == 3                      # bound never grew


def test_eviction_needs_strictly_lower_class():
    """No strictly-lower class queued → the arrival itself sheds, even
    for gold (gold never evicts gold)."""
    gate = AdmissionGate(queue_max=2, batch_max=2, batch_wait_ms=1000)
    assert gate.submit(_qreq(0, SP.QOS_GOLD))[0] == "admitted"
    assert gate.submit(_qreq(1, SP.QOS_GOLD))[0] == "admitted"
    assert gate.submit(_qreq(2, SP.QOS_GOLD))[0] == "shed_queue_full"
    assert gate.submit(_qreq(3, SP.QOS_BRONZE))[0] == "shed_queue_full"
    assert gate.pop_evicted() == []


def test_eviction_policy_deterministic_replay():
    """Same arrival sequence, same verdicts AND same victims — the
    QoS refinement keeps the gate's determinism contract."""
    def drive():
        gate = AdmissionGate(queue_max=4, batch_max=2,
                             batch_wait_ms=1000,
                             qos_budgets={SP.QOS_BRONZE: 3})
        rng = np.random.default_rng(7)
        verdicts, victims = [], []
        for i in range(32):
            qos = int(rng.integers(0, 3))
            verdicts.append(gate.submit(_qreq(i, qos,
                                              arrival=float(i)))[0])
            victims += [v.req_id for v in gate.pop_evicted()]
        return verdicts, victims

    assert drive() == drive()


def test_default_budgets_keep_pre_qos_behavior():
    """No budgets configured → every class's budget is the whole
    queue and single-class traffic sees exactly the pre-QoS gate."""
    gate = AdmissionGate(queue_max=3, batch_max=2, batch_wait_ms=1000)
    assert [gate.submit(_qreq(i))[0] for i in range(4)] \
        == ["admitted"] * 3 + ["shed_queue_full"]
    assert gate.pop_evicted() == []


# --------------------------------------------------- dedup window
def test_dedup_hedge_before_serve_and_after_commit():
    win = dedup_mod.DedupWindow(capacity=8)
    # hedge-before-serve: the loser of the claim race is INFLIGHT.
    assert win.claim(5) == (dedup_mod.NEW, None)
    state, cached = win.claim(5)
    assert state == dedup_mod.INFLIGHT and cached is None
    # hedge-after-commit: the loser gets the cached answer.
    preds = np.array([1.5, -2.0])
    win.commit(5, 3, preds)
    state, cached = win.claim(5)
    assert state == dedup_mod.COMMITTED
    assert cached[0] == 3
    np.testing.assert_array_equal(cached[1], preds)
    st = win.stats()
    assert st["claims"] == 3 and st["duplicates"] == 2
    assert st["commits"] == 1


def test_dedup_release_reopens_failed_serve():
    """A shed/timeout/error winner releases its claim: the retry must
    NOT be suppressed by its own failed first attempt."""
    win = dedup_mod.DedupWindow(capacity=8)
    assert win.claim(9)[0] == dedup_mod.NEW
    win.release(9)
    assert win.claim(9)[0] == dedup_mod.NEW


def test_dedup_eviction_prefers_committed_entries():
    win = dedup_mod.DedupWindow(capacity=2)
    win.claim(1)
    win.commit(1, 1, np.zeros(1))
    win.claim(2)                       # inflight
    win.claim(3)                       # evicts committed 1, not 2
    assert win.claim(2)[0] == dedup_mod.INFLIGHT
    assert win.claim(1)[0] == dedup_mod.NEW     # evicted → re-claimable
    assert win.stats()["evictions"] >= 1


def test_dedup_property_hedge_interleavings():
    """The satellite property test: seeded random interleavings of
    (first send, hedge copy, commit, lost-reply retry) driven against
    a bounded window, checked against a naive UNBOUNDED model.

    Invariants:
    * a key serves more than once ONLY via a documented reopening —
      eviction under capacity pressure or release after a failed
      serve; every extra serve is bounded by those two counts;
    * a committed duplicate always returns the exact committed payload.
    """
    for seed in range(6):
        rng = np.random.default_rng(seed)
        win = dedup_mod.DedupWindow(capacity=4)
        serves: dict[int, int] = {}          # key -> NEW claims
        committed: dict[int, np.ndarray] = {}  # reference payloads
        releases = 0
        keys = list(range(1, 13))
        for _ in range(400):
            k = int(rng.choice(keys))
            op = rng.random()
            if op < 0.6:                     # a copy arrives (first or
                state, cached = win.claim(k)  # hedge or late retry)
                if state == dedup_mod.NEW:
                    serves[k] = serves.get(k, 0) + 1
                    # the winner either commits or loses its reply
                    if rng.random() < 0.8:
                        payload = np.full(2, float(k))
                        win.commit(k, k, payload)
                        committed[k] = payload
                    else:
                        win.release(k)
                        releases += 1
                elif state == dedup_mod.COMMITTED:
                    np.testing.assert_array_equal(
                        cached[1], committed[k])
            # (claims landing INFLIGHT are the suppressed storm)
        # with 12 keys against capacity 4 there MUST have been
        # evictions, so the degradation path is exercised.
        assert win.stats()["evictions"] > 0
        total_serves = sum(serves.values())
        assert total_serves >= len(serves)   # every touched key served
        # exactly-once modulo the two DOCUMENTED reopenings: an extra
        # serve needs an eviction or a failed-serve release behind it.
        assert total_serves - len(serves) \
            <= win.stats()["evictions"] + releases


def test_dedup_exactly_once_inside_window():
    """Storm WITHOUT eviction pressure: copies*keys claims, exactly
    one NEW per key — the window is an exactly-once filter as long as
    the key stays resident."""
    win = dedup_mod.DedupWindow(capacity=64)
    news = 0
    for copy in range(4):
        for k in range(16):
            state, _ = win.claim(k)
            if state == dedup_mod.NEW:
                news += 1
                win.commit(k, 1, np.zeros(1))
    assert news == 16
    assert win.stats()["duplicates"] == 3 * 16
    assert win.stats()["evictions"] == 0


# ------------------------------------------------ server end to end
def test_serve_rank_duplicate_reply_bitwise_cached(tmp_path):
    """A replayed idempotency key answers STATUS_DUPLICATE carrying
    the bitwise-identical cached prediction and version — the wire
    contract the hedging client's verifier checks."""
    _make_store(tmp_path / "m")
    rank = _start_rank(tmp_path / "m")
    try:
        x = np.arange(8, dtype=np.float32)
        with socket.create_connection((rank.host, rank.port),
                                      timeout=10) as s:
            SP.PredictRequest(1, 0, x, idem_key=77).send(s)
            first = SP.PredictReply.recv(s)
            assert first.status == SP.STATUS_OK
            SP.PredictRequest(2, 0, x, idem_key=77).send(s)
            dup = SP.PredictReply.recv(s)
            assert dup.status == SP.STATUS_DUPLICATE
            assert dup.req_id == 2
            assert dup.model_version == first.model_version
            assert dup.predictions.tobytes() \
                == first.predictions.tobytes()
        st = rank.stats()
        assert st["dedup"]["duplicates"] == 1
        assert st["dedup"]["commits"] == 1
    finally:
        rank.stop()


def test_serve_rank_per_class_books_and_budgets(tmp_path):
    """Per-class counters on the rank's stats: a bronze request over
    its budget is shed and booked under bronze, gold is served and
    booked under gold."""
    _make_store(tmp_path / "m")
    rank = _start_rank(tmp_path / "m", slow_ms=100, batch_max=1,
                       qos_budgets={SP.QOS_BRONZE: 1})
    try:
        x = np.arange(8, dtype=np.float32)
        socks = [socket.create_connection((rank.host, rank.port),
                                          timeout=10)
                 for _ in range(4)]
        try:
            # occupy the worker (slow_ms=100, batch_max=1) so the
            # bronze pair stays QUEUED — then budget 1 sheds the
            # second bronze while gold still gets room.
            SP.PredictRequest(1, 0, x, qos=SP.QOS_SILVER).send(socks[0])
            import time as _time
            _time.sleep(0.05)
            SP.PredictRequest(2, 0, x, qos=SP.QOS_BRONZE).send(socks[1])
            _time.sleep(0.02)
            SP.PredictRequest(3, 0, x, qos=SP.QOS_BRONZE).send(socks[2])
            SP.PredictRequest(4, 0, x, qos=SP.QOS_GOLD).send(socks[3])
            statuses = {}
            for i, s in enumerate(socks):
                s.settimeout(10)
                statuses[i + 1] = SP.PredictReply.recv(s).status
            assert statuses[1] == SP.STATUS_OK
            assert statuses[2] == SP.STATUS_OK
            assert statuses[3] == SP.STATUS_SHED
            assert statuses[4] == SP.STATUS_OK
            pc = rank.stats()["per_class"]
            assert pc["bronze"]["offered"] == 2
            assert pc["bronze"]["shed_queue_full"] == 1
            assert pc["gold"]["admitted"] == 1
            assert rank.stats()["qos_budgets"]["bronze"] == 1
        finally:
            for s in socks:
                s.close()
    finally:
        rank.stop()


def test_run_storm_zero_double_serves(tmp_path):
    """The loadgen hedge storm against one rank: every key served
    exactly once, every suppressed copy a typed Duplicate, cached
    answers bitwise-verified."""
    from rabit_tpu.tools.loadgen import run_storm

    _make_store(tmp_path / "m", dim=16)
    rank = _start_rank(tmp_path / "m")
    try:
        rep = run_storm(f"{rank.host}:{rank.port}", keys=6, copies=3,
                        dim=16, seed=3,
                        verify_dir=str(tmp_path / "m"))
        assert rep["ok_serves"] == 6
        assert rep["double_served"] == 0
        assert rep["unserved_keys"] == 0
        assert rep["duplicates"] == 12
        assert rep["wrong"] == 0
        assert rep["verified"] >= 6
    finally:
        rank.stop()


# ---------------------------------------------------------- the router
def _mk_router(factor=3.0, checks=2):
    from rabit_tpu.tools.loadgen import EndpointSet, Router

    eps = EndpointSet([("h", 1), ("h", 2), ("h", 3)], None)
    return Router(eps, factor=factor, checks=checks), eps.all()


def test_router_conviction_hysteresis_and_reinstatement():
    router, eps = _mk_router(factor=3.0, checks=2)
    slow = eps[0]
    # one bad round is NOT a conviction (hysteresis)
    router.observe({slow: 10.0})
    assert not router.convicted
    router.observe({slow: 10.0})
    assert router.convicted == {slow}
    assert router.convictions == 1
    # recovery: below factor/2 held for `checks` rounds reinstates
    router.observe({slow: 1.0})
    assert router.convicted == {slow}
    router.observe({slow: 1.0})
    assert not router.convicted
    assert router.reinstatements == 1


def test_router_interrupted_streaks_reset():
    router, eps = _mk_router(checks=3)
    slow = eps[1]
    router.observe({slow: 9.0})
    router.observe({slow: 9.0})
    router.observe({slow: 1.0})       # streak broken
    router.observe({slow: 9.0})
    router.observe({slow: 9.0})
    assert not router.convicted       # needs 3 CONSECUTIVE
    router.observe({slow: 9.0})
    assert router.convicted == {slow}


def test_router_shifts_share_off_convicted():
    router, eps = _mk_router(checks=1)
    slow = eps[0]
    router.observe({slow: 10.0})
    assert router.convicted == {slow}
    picks = [router.pick() for _ in range(90)]
    share = picks.count(slow) / len(picks)
    # weight 0.25 vs 1+1 → ~11% of traffic, never zero (samples must
    # keep flowing so reinstatement evidence exists)
    assert 0.0 < share < 0.2
    snap = router.snapshot()
    assert snap["convicted"] == ["h:1"]
    assert snap["convictions"] == 1


def test_router_pick_excludes_hedge_primary():
    router, eps = _mk_router()
    for _ in range(12):
        assert router.pick(exclude=eps[0]) != eps[0]


# ------------------------------------------------ books on the obs plane
def test_livetable_folds_qos_counters():
    from rabit_tpu.obs import LiveTable

    lt = LiveTable()
    lt.ingest(0, 1.0, {
        "rank": 0,
        "counters": {"serve.requests.ok": 10,
                     "serve.qos.gold.ok": 4,
                     "serve.qos.gold.shed": 1,
                     "serve.qos.bronze.shed": 5},
        "gauges": {"serve.queue_depth": 1}})
    serve = lt.report()["0"]["serve"]
    assert serve["qos"] == {"gold": {"ok": 4, "shed": 1},
                            "bronze": {"shed": 5}}


def test_serve_straggler_scores_fold():
    from rabit_tpu.obs import serve_straggler_scores

    rows = [(0, {"gauges": {"serve.svc_ewma_ms": 20.0}}),
            (1, {"gauges": {"serve.svc_ewma_ms": 100.0}}),
            (2, {"gauges": {"serve.svc_ewma_ms": 20.0}})]
    scores = serve_straggler_scores(rows)
    assert scores[1] == 5.0 and scores[0] == 1.0
    # a singleton is its own median: no verdict
    assert serve_straggler_scores(rows[:1]) == {}
    # ranks without the gauge are simply absent
    assert serve_straggler_scores(
        rows + [(3, {"gauges": {}})]).keys() == {0, 1, 2}


def test_tracker_renders_qos_series_and_merged_scores():
    """serve.qos.<class>.<status> counters render as ONE labeled
    series, and the serving-plane svc-EWMA fold lands in
    rabit_straggler_score for a serve-only job (no training spans at
    all)."""
    import collections
    import threading as _threading

    from rabit_tpu.tracker.tracker import Tracker

    t = Tracker.__new__(Tracker)
    job = t._default_job()
    job.touched = True
    t._svc_lock = _threading.Lock()
    t._svc_counters = collections.Counter()
    t._serve_slo_target = 0.99
    t._elastic = {}
    for rank, ewma in ((0, 20.0), (1, 100.0), (2, 20.0)):
        job._live.ingest(rank, 1.0, {
            "rank": rank,
            "counters": {"serve.requests.ok": 50,
                         "serve.qos.gold.ok": 30,
                         "serve.qos.bronze.shed": 20},
            "gauges": {"serve.svc_ewma_ms": ewma}})
    text = t._render_metrics()
    assert ('rabit_serve_qos_requests_total{job="default",qos="gold",'
            'rank="0",status="ok"} 30') in text
    assert ('rabit_serve_qos_requests_total{job="default",'
            'qos="bronze",rank="1",status="shed"} 20') in text
    assert "# TYPE rabit_serve_qos_requests_total counter" in text
    # the split counters never double-render under their raw names
    assert "rabit_serve_qos_gold_ok" not in text
    # serve-only straggler scores: rank 1 is 5x the fleet median
    assert 'rabit_straggler_score{job="default",rank="1"} 5\n' in text
    assert 'rabit_straggler_score{job="default",rank="0"} 1\n' in text
    status = t._render_status()
    assert status["jobs"]["default"]["straggler_scores"]["1"] == 5.0


# ------------------------------------------------- postmortem fold
def _loadgen_report(offered, ok, shed=0, timeout=0, error=0,
                    duplicate=0, per_class=None, hedges=None):
    return {"offered": offered, "ok": ok, "shed": shed,
            "timeout": timeout, "error": error, "duplicate": duplicate,
            "wrong": 0, "double_served": 0,
            "per_class": per_class or {},
            "hedges": hedges or {}}


def test_postmortem_folds_serving_books():
    from rabit_tpu.tools.postmortem import (fold_serving_books,
                                            reconstruct)

    reports = [
        _loadgen_report(
            100, 90, shed=10,
            per_class={"gold": {"offered": 40, "ok": 40, "shed": 0,
                                "timeout": 0, "error": 0,
                                "duplicate": 0},
                       "bronze": {"offered": 60, "ok": 50, "shed": 10,
                                  "timeout": 0, "error": 0,
                                  "duplicate": 0}},
            hedges={"fired": 7, "wins": 5, "stray_replies": 3,
                    "cross_rank_serves": 2}),
        _loadgen_report(
            50, 45, duplicate=5,
            per_class={"gold": {"offered": 50, "ok": 45, "shed": 0,
                                "timeout": 0, "error": 0,
                                "duplicate": 4}},   # imbalanced!
            hedges={"fired": 1, "wins": 1, "stray_replies": 0,
                    "cross_rank_serves": 0}),
    ]
    folded = fold_serving_books(reports)
    assert folded["reports"] == 2
    assert folded["totals"]["offered"] == 150
    assert folded["totals"]["ok"] == 135
    assert folded["totals"]["balanced"] is True
    assert folded["hedges"] == {"fired": 8, "wins": 6,
                                "stray_replies": 3,
                                "cross_rank_serves": 2}
    assert folded["per_class"]["bronze"]["balanced"] is True
    # gold: offered 90 vs ok 85 + dup 4 = 89 → the fold NAMES the hole
    assert folded["per_class"]["gold"]["balanced"] is False
    verdict = reconstruct([], serving_reports=reports)
    assert verdict["serving"]["totals"]["offered"] == 150
    assert fold_serving_books([]) is None
    assert fold_serving_books([{"not": "a report"}]) is None


def test_postmortem_loads_and_renders_serving_reports(tmp_path):
    import io

    from rabit_tpu.tools import postmortem as pm

    rep = _loadgen_report(
        10, 10,
        per_class={"silver": {"offered": 10, "ok": 10, "shed": 0,
                              "timeout": 0, "error": 0,
                              "duplicate": 0}},
        hedges={"fired": 2, "wins": 2, "stray_replies": 1,
                "cross_rank_serves": 1})
    (tmp_path / "loadgen.steady.json").write_text(json.dumps(rep))
    (tmp_path / "loadgen.bogus.json").write_text("{not json")
    reports = pm.load_serving_reports(str(tmp_path))
    assert len(reports) == 1
    verdict = pm.reconstruct([], serving_reports=reports)
    buf = io.StringIO()
    pm.render(verdict, out=buf)
    out = buf.getvalue()
    assert "serving books (1 report(s))" in out
    assert "class silver: offered=10" in out and "balanced" in out
    assert "hedges: fired=2" in out


# --------------------------------------------------------- CLI seams
def test_parse_qos_budgets_and_slow_task_ms():
    from rabit_tpu.serve.server import parse_qos_budgets
    from rabit_tpu.tools.serve import parse_slow_task_ms

    assert parse_qos_budgets("gold:16,silver:8,bronze:2") \
        == {SP.QOS_GOLD: 16, SP.QOS_SILVER: 8, SP.QOS_BRONZE: 2}
    assert parse_qos_budgets("") == {}
    with pytest.raises(ValueError):
        parse_qos_budgets("platinum:4")
    assert parse_slow_task_ms("s001:100,s002:5.5") \
        == {"s001": 100.0, "s002": 5.5}
    assert parse_slow_task_ms("") == {}
    with pytest.raises(ValueError):
        parse_slow_task_ms("s001")


def test_parse_qos_mix_bins():
    from rabit_tpu.tools.loadgen import parse_qos_mix

    bins = parse_qos_mix("gold:1,silver:1,bronze:2")
    assert [q for _, q in bins] \
        == [SP.QOS_GOLD, SP.QOS_SILVER, SP.QOS_BRONZE]
    assert bins[-1][0] == pytest.approx(1.0)
    assert bins[0][0] == pytest.approx(0.25)
    with pytest.raises(ValueError):
        parse_qos_mix("copper:1")
    with pytest.raises(ValueError):
        parse_qos_mix("gold:0")


def test_chaos_serve_sites_registered():
    from rabit_tpu import chaos as chaos_mod
    from rabit_tpu.chaos.plan import parse_plan

    assert chaos_mod.SITE_SERVE_REQ in chaos_mod.SITES
    assert chaos_mod.SITE_SERVE_REPLY in chaos_mod.SITES
    from rabit_tpu.utils.checks import RabitError

    plan = parse_plan("3:reset@serve_req=1.0*1;stall@serve_reply=1.0*1",
                      "loadgen")
    assert plan is not None
    with pytest.raises(RabitError):
        # the serving wire admits only reset/stall
        parse_plan("3:flip@serve_req=1.0", "loadgen")


# ------------------------------------------------------- the slow gate
@pytest.mark.slow
def test_qos_soak_gate():
    """The headline gate: straggler-aware routing (>=30% share moved
    off the slow rank) → mixed-class overload (gold SLO holds, bronze
    sheds, per-class books exact) → forced hedge storm (zero double
    serves) → hedged tail run → serving-wire chaos pairing."""
    from rabit_tpu.tools.soak import main as soak_main

    assert soak_main(["--qos", "--rounds", "1"]) == 0
