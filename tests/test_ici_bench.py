"""Wiring test for the in-program collective bandwidth harness."""
from rabit_tpu.tools.ici_bench import bench_impl


def test_psum_and_ring_impls_run():
    for impl in ("psum", "ring"):
        dt = bench_impl(impl, 4, 1024, reps=3)
        assert dt > 0


def test_world1_degenerate():
    assert bench_impl("psum", 1, 256, reps=2) > 0
