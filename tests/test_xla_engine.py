"""XLA engine tests: single-process semantics + multi-process device path."""
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import rabit_tpu


@pytest.fixture
def xla_world1():
    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="xla")
    yield
    rabit_tpu.finalize()


def test_world1_identity(xla_world1):
    assert rabit_tpu.get_world_size() == 1
    assert rabit_tpu.get_rank() == 0
    x = jnp.arange(8, dtype=jnp.float32)
    out = rabit_tpu.allreduce(x, rabit_tpu.SUM)
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(8, dtype=np.float32))
    a = np.ones(4)
    assert rabit_tpu.allreduce(a, rabit_tpu.MAX) is a


def test_pallas_ring_routing():
    """rabit_device_impl=pallas_ring routes large supported allreduces
    through the ring kernel and leaves small payloads / unsupported ops
    on psum (the latency-bound regime)."""
    from rabit_tpu.engine.xla import XLAEngine
    from rabit_tpu.ops import ReduceOp

    eng = XLAEngine()
    eng.init({"rabit_device_impl": "pallas_ring",
              "rabit_pallas_min_bytes": 4096})
    try:
        assert eng._use_pallas_ring((2048,), "float32", ReduceOp.SUM)
        assert eng._use_pallas_ring((64, 64), "float32", ReduceOp.MAX)
        # below the size gate
        assert not eng._use_pallas_ring((16,), "float32", ReduceOp.SUM)
        # no kernel combine for bitwise ops
        assert not eng._use_pallas_ring((2048,), "int32", ReduceOp.BITOR)
    finally:
        eng.shutdown()
    # default impl: everything stays on psum
    eng2 = XLAEngine()
    eng2.init({})
    try:
        assert not eng2._use_pallas_ring((1 << 20,), "float32",
                                         ReduceOp.SUM)
    finally:
        eng2.shutdown()
    with pytest.raises(Exception, match="rabit_device_impl"):
        bad = XLAEngine()
        bad.init({"rabit_device_impl": "warp"})


def test_world1_prepare_fun_called(xla_world1):
    called = []
    x = jnp.zeros(3)
    rabit_tpu.allreduce(x, rabit_tpu.SUM, prepare_fun=lambda: called.append(1))
    assert called == [1]


def test_world1_checkpoint_roundtrip(xla_world1):
    version, model = rabit_tpu.load_checkpoint()
    assert version == 0 and model is None
    rabit_tpu.checkpoint({"w": [1, 2, 3]})
    version, model = rabit_tpu.load_checkpoint()
    assert version == 1 and model == {"w": [1, 2, 3]}


def test_world1_broadcast(xla_world1):
    assert rabit_tpu.broadcast({"k": 7}, 0) == {"k": 7}


@pytest.mark.parametrize("world", [2, 3])
def test_multiprocess_xla_engine(world):
    """N processes: tracker control plane + Gloo-backed XLA data plane."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(world, [sys.executable, "tests/workers/check_xla.py"])
    assert code == 0


def test_multiprocess_xla_engine_native_inner(request):
    """XLA data plane over the C++ robust engine control plane."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(2, [sys.executable, "tests/workers/check_xla.py"],
                  extra_env={"RABIT_INNER": "native"})
    assert code == 0


def test_xla_worker_death_relaunch_resume(request):
    """The device-plane fault story end-to-end: rank 1 dies mid-run, the
    survivors' device collective fails and degrades to the host
    transport, the keepalive launcher restarts rank 1, which rejoins
    degraded and resumes from the last checkpoint (reference recovery
    contract: src/allreduce_robust.cc:73-105)."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native"}, watchdog_sec=20)
    assert code == 0


def test_xla_worker_death_world4_blocked_peer(request):
    """World 4: a peer death leaves rank 3 BLOCKED inside its Gloo
    collective (its direct transport peers are alive — they abandoned the
    collective after degrading — so no error ever reaches it).  The
    tracker watchdog is the designed answer: it reports the silent rank,
    the launcher kills and restarts it, and the relaunch (flagged by the
    tracker) rejoins degraded and resumes from the checkpoint."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(4, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native"}, watchdog_sec=20)
    assert code == 0


def test_xla_rank0_death_relaunch_resume(request):
    """Rank 0 dies mid-run.  Because the JAX coordination service is
    hosted in the TRACKER (cmd=jaxsvc), losing rank 0 is an ordinary
    recoverable peer death — survivors degrade instead of being
    LOG(FATAL)-terminated by the error-polling thread, the relaunch
    rejoins, and the next checkpoint re-forms the device plane."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "0:2"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_whole_job_restart_reforms(request):
    """Every rank flagged as a mid-job relaunch (long-lived tracker +
    coordinated platform restart): all come up degraded, and the first
    checkpoint boundary forms a device plane from nothing — the
    permanent performance cliff of the round-2 design is gone."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "none",
                             "RABIT_XLA_FORCE_RELAUNCH": "1"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_reform_disabled_stays_degraded(request):
    """RABIT_DEVICE_REFORM=0 keeps the round-2 contract: a relaunched
    job runs degraded (host transport) to completion, no re-formation."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_DEVICE_REFORM": "0"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_two_deaths_different_iterations(request):
    """Two workers die at different iterations: each relaunch rejoins
    degraded and catches up from its own checkpoint version while the
    other death is still being recovered (the die-different-versions
    matrix of test/test.mk, lifted onto the XLA engine)."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(4, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "1:1;3:2"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_world8_two_simultaneous_deaths(request):
    """World 8, two workers die at the SAME iteration (die-same matrix
    of test/test.mk on the XLA engine at the verdict-requested world):
    both relaunches rejoin degraded, one checkpoint boundary re-forms
    the 8-process device plane, and the numerics stay exact."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(8, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "2:2;5:2"},
                  watchdog_sec=30)
    assert code == 0


def test_xla_world8_death_during_reform(request):
    """World 8: rank 1 dies mid-run; at the checkpoint boundary the
    plane re-forms, and rank 6 dies INSIDE the replayed post-reform
    round (engine/xla.py's replayed-round/stale-group branches) — the
    survivors must degrade again, take rank 6's relaunch back in, and
    re-form once more."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(8, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "1:1",
                             "RABIT_XLA_DIE_ON_REFORM": "6"},
                  watchdog_sec=30)
    assert code == 0


def test_xla_world8_rank0_then_another_consecutive_checkpoints(request):
    """World 8: rank 0 (coordination-sensitive) dies at iteration 1 and
    rank 4 at iteration 2 — deaths in consecutive checkpoint spans, each
    recovered while the previous recovery's reform is still fresh."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(8, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "0:1;4:2"},
                  watchdog_sec=30)
    assert code == 0


def test_private_bindings_probe(monkeypatch):
    """The jaxlib-capability probe is a try-call, not a doc-grep: it
    must track what the binding actually ACCEPTS, surviving docstring
    wording churn and stripped docstrings (python -OO)."""
    from jax._src.lib import _jax as jaxlib_ext

    from rabit_tpu.engine.xla import XLAEngine

    class _Client:
        pass

    def accepts(addr, node_id, *, init_timeout,
                shutdown_on_destruction, recoverable):
        return _Client()

    def rejects(addr, node_id, *, init_timeout):  # no recoverable kwargs
        return _Client()

    def env_error(addr, node_id, *, init_timeout,
                  shutdown_on_destruction, recoverable):
        raise RuntimeError("address unreachable")  # kwargs were accepted

    monkeypatch.setattr(
        jaxlib_ext, "get_distributed_runtime_client", accepts)
    assert XLAEngine._private_bindings_ok() is True
    monkeypatch.setattr(
        jaxlib_ext, "get_distributed_runtime_client", rejects)
    assert XLAEngine._private_bindings_ok() is False
    monkeypatch.setattr(
        jaxlib_ext, "get_distributed_runtime_client", env_error)
    assert XLAEngine._private_bindings_ok() is True


def test_xla_death_inside_group_formation(request):
    """The window the design admits is awkward: a worker finishes the
    tracker round but dies BEFORE the JAX group forms.  Survivors must
    surface the failed formation within the capped first-formation
    timeout (or be watchdog-recovered out of the blocked connect),
    start degraded, complete the run on the host transport, and the
    checkpoint boundary must re-form the device plane (reference
    analogue: death during recovery, the die-hard matrix of
    test/test.mk)."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "none",
                             "RABIT_XLA_DIE_FORMATION": "1"},
                  watchdog_sec=20)
    assert code == 0


def _run_adopt_workers(world: int, mode: str) -> list:
    """Spawn ``world`` processes that self-initialize jax.distributed
    (CPU/Gloo) and then adopt it through init(rabit_engine="xla")."""
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for r in range(world):
        env = dict(os.environ)
        env.update({"ADOPT_COORD": f"127.0.0.1:{port}",
                    "ADOPT_RANK": str(r), "ADOPT_WORLD": str(world),
                    "ADOPT_MODE": mode})
        env.pop("RABIT_TRACKER_URI", None)
        procs.append(subprocess.Popen(
            [sys.executable, "tests/workers/adopt_worker.py"], env=env))
    return [p.wait(timeout=300) for p in procs]


def test_xla_adopt_mode_world3():
    """Pure adopt mode at world 3: rank/world adoption, numpy in-place
    via device reduction, object broadcast over
    _device_byte_broadcast — the pod path doc/scaling.md promises."""
    assert _run_adopt_workers(3, "ok") == [0, 0, 0]


def test_xla_adopt_mode_peer_death_raises():
    """Adopt mode has no host transport: a peer's death must surface as
    the documented RuntimeError on the survivors' next device
    collective (engine/xla.py _host_degrade), never hang or silently
    degrade."""
    codes = _run_adopt_workers(3, "peerdeath")
    assert codes[1] == 7           # the victim's own exit
    assert codes[0] == 0 and codes[2] == 0, codes


def _run_mixed_workers(world: int, mode: str, monkeypatch) -> list:
    """MIXED mode: a tracker control plane AND a worker-initialized
    jax.distributed world.  The tracker runs in-process with rank
    pinning on (it reads the env at assignment time)."""
    import socket
    import subprocess

    from rabit_tpu.tracker.tracker import Tracker

    monkeypatch.setenv("RABIT_TRACKER_PIN_RANKS", "1")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tracker = Tracker(world)
    tracker.start()
    try:
        procs = []
        for r in range(world):
            env = dict(os.environ)
            env.update(tracker.worker_env(task_id=""))
            env.pop("RABIT_TASK_ID", None)  # the engine must self-register
            env.update({"MIXED_COORD": f"127.0.0.1:{port}",
                        "MIXED_RANK": str(r), "MIXED_WORLD": str(world),
                        "MIXED_MODE": mode})
            if mode == "relaunch":
                env["RABIT_RELAUNCH"] = "1"
            procs.append(subprocess.Popen(
                [sys.executable, "tests/workers/mixed_worker.py"], env=env))
        return [p.wait(timeout=300) for p in procs]
    finally:
        tracker.stop()


def test_xla_mixed_mode_world3(monkeypatch):
    """MIXED mode end-to-end: the engine adopts the external JAX world
    for the device plane, registers with task_id = jax.process_index(),
    and rank pinning aligns the control-plane rank with it — numpy ops
    and checkpoints ride the fault-tolerant host engine while jax.Array
    ops ride the device plane (the contract engine/xla.py documents for
    tracker + pre-initialized JAX)."""
    assert _run_mixed_workers(3, "ok", monkeypatch) == [0, 0, 0]


def test_xla_mixed_mode_rank_mismatch_degrades(monkeypatch):
    """Misaligned numberings (explicit task_ids reversed) must degrade
    EVERY rank to the host transport by consensus — including rank 1,
    whose own mesh check passes under the reversal — never crash some
    ranks or split-brain the collectives."""
    assert _run_mixed_workers(3, "mismatch", monkeypatch) == [0, 0, 0]


def test_xla_mixed_mode_relaunch_stays_adopted(monkeypatch):
    """A mixed-mode relaunch (RABIT_RELAUNCH set) must still be marked
    adopted — otherwise its checkpoint-time _maybe_reform would issue
    host-plane protocol ops the adopted survivors never pair with —
    and must run degraded permanently without joining the init-time
    mesh consensus (which only first-life ranks reach)."""
    assert _run_mixed_workers(3, "relaunch", monkeypatch) == [0, 0, 0]
