"""XLA engine tests: single-process semantics + multi-process device path."""
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import rabit_tpu


@pytest.fixture
def xla_world1():
    if rabit_tpu.initialized():
        rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="xla")
    yield
    rabit_tpu.finalize()


def test_world1_identity(xla_world1):
    assert rabit_tpu.get_world_size() == 1
    assert rabit_tpu.get_rank() == 0
    x = jnp.arange(8, dtype=jnp.float32)
    out = rabit_tpu.allreduce(x, rabit_tpu.SUM)
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(8, dtype=np.float32))
    a = np.ones(4)
    assert rabit_tpu.allreduce(a, rabit_tpu.MAX) is a


def test_world1_prepare_fun_called(xla_world1):
    called = []
    x = jnp.zeros(3)
    rabit_tpu.allreduce(x, rabit_tpu.SUM, prepare_fun=lambda: called.append(1))
    assert called == [1]


def test_world1_checkpoint_roundtrip(xla_world1):
    version, model = rabit_tpu.load_checkpoint()
    assert version == 0 and model is None
    rabit_tpu.checkpoint({"w": [1, 2, 3]})
    version, model = rabit_tpu.load_checkpoint()
    assert version == 1 and model == {"w": [1, 2, 3]}


def test_world1_broadcast(xla_world1):
    assert rabit_tpu.broadcast({"k": 7}, 0) == {"k": 7}


@pytest.mark.parametrize("world", [2, 3])
def test_multiprocess_xla_engine(world):
    """N processes: tracker control plane + Gloo-backed XLA data plane."""
    from rabit_tpu.tracker.launch_local import launch

    code = launch(world, [sys.executable, "tests/workers/check_xla.py"])
    assert code == 0


def test_multiprocess_xla_engine_native_inner(request):
    """XLA data plane over the C++ robust engine control plane."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(2, [sys.executable, "tests/workers/check_xla.py"],
                  extra_env={"RABIT_INNER": "native"})
    assert code == 0


def test_xla_worker_death_relaunch_resume(request):
    """The device-plane fault story end-to-end: rank 1 dies mid-run, the
    survivors' device collective fails and degrades to the host
    transport, the keepalive launcher restarts rank 1, which rejoins
    degraded and resumes from the last checkpoint (reference recovery
    contract: src/allreduce_robust.cc:73-105)."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native"}, watchdog_sec=20)
    assert code == 0


def test_xla_worker_death_world4_blocked_peer(request):
    """World 4: a peer death leaves rank 3 BLOCKED inside its Gloo
    collective (its direct transport peers are alive — they abandoned the
    collective after degrading — so no error ever reaches it).  The
    tracker watchdog is the designed answer: it reports the silent rank,
    the launcher kills and restarts it, and the relaunch (flagged by the
    tracker) rejoins degraded and resumes from the checkpoint."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(4, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native"}, watchdog_sec=20)
    assert code == 0


def test_xla_rank0_death_relaunch_resume(request):
    """Rank 0 dies mid-run.  Because the JAX coordination service is
    hosted in the TRACKER (cmd=jaxsvc), losing rank 0 is an ordinary
    recoverable peer death — survivors degrade instead of being
    LOG(FATAL)-terminated by the error-polling thread, the relaunch
    rejoins, and the next checkpoint re-forms the device plane."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "0:2"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_whole_job_restart_reforms(request):
    """Every rank flagged as a mid-job relaunch (long-lived tracker +
    coordinated platform restart): all come up degraded, and the first
    checkpoint boundary forms a device plane from nothing — the
    permanent performance cliff of the round-2 design is gone."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "none",
                             "RABIT_XLA_FORCE_RELAUNCH": "1"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_reform_disabled_stays_degraded(request):
    """RABIT_DEVICE_REFORM=0 keeps the round-2 contract: a relaunched
    job runs degraded (host transport) to completion, no re-formation."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(3, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_DEVICE_REFORM": "0"},
                  watchdog_sec=20)
    assert code == 0


def test_xla_two_deaths_different_iterations(request):
    """Two workers die at different iterations: each relaunch rejoins
    degraded and catches up from its own checkpoint version while the
    other death is still being recovered (the die-different-versions
    matrix of test/test.mk, lifted onto the XLA engine)."""
    from rabit_tpu.tracker.launch_local import launch

    request.getfixturevalue("native_lib")
    code = launch(4, [sys.executable, "tests/workers/xla_restart.py"],
                  extra_env={"RABIT_INNER": "native",
                             "RABIT_XLA_DIE": "1:1;3:2"},
                  watchdog_sec=20)
    assert code == 0
