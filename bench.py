"""Benchmark harness — prints ONE JSON line on stdout.

Benchmarks the flagship workload: full k-means iterations (assign +
accumulate + recompute, the per-iteration work of the reference app,
reference: rabit-learn/kmeans/kmeans.cc:121-157).  The framework path is
``kmeans.device_iterations`` — the device-resident chained loop the app
uses via ``kmeans.run(device_chain=...)`` — with the fused Pallas stats
kernel (rabit_tpu/ops/kmeans_kernel.py, single HBM read per iteration,
bf16 compute / f32 accumulate) or the XLA two-matmul pass, whichever is
faster on the local chip.  The baseline is the reference's design point
— host-side compute feeding the collective — implemented as strong
*vectorized* numpy (already far faster than the reference's actual
per-row C++ loop, so vs_baseline is conservative).

Timing method: the axon-tunneled TPU adds a fixed ~95 ms round trip to
every fetched execution, so a single chained run over-reports per-iter
cost.  We time a short (ITERS_SHORT) and a long (ITERS_LONG) chain of
the same recurrent loop and take (T_long - T_short) / (ITERS_LONG -
ITERS_SHORT), which cancels the fixed cost exactly; the loop is a true
recurrence (centroids feed back), so XLA cannot hoist the body.

Measurement discipline (round 4): candidates are interleaved across
TRIALS difference-timing trials (so a load burst hits every candidate,
not one), the official number is the best candidate's MEDIAN, and the
JSON carries the relative spread of that candidate's trials.  A
recorded single-chip anchor (ANCHOR_MS_PER_ITER, the quiet-box
HBM-roofline measurement in doc/benchmarks.md) is cross-checked: when
the winner deviates from it by more than ANCHOR_TOL the JSON is marked
``"suspect"`` so a round-over-round swing can be told apart from a real
regression.  The per-candidate table goes to stderr; candidates that
fail to run or fail the numerics guard are reported there too, never
silently dropped.

A numerics guard runs each candidate against the float32 XLA oracle for
GUARD_ITERS iterations and requires the final centroids to match within
GUARD_TOL relative Frobenius error.

Metric: million points/sec through one full k-means iteration
(k=64 clusters, d=256 features, 512k points densified from 32-nnz rows).
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

import numpy as np

N, D, K, NNZ = 1 << 19, 256, 64, 32
ITERS_SHORT, ITERS_LONG = 50, 500
TRIALS = 7  # round 5: two extra interleaved trials — the tunneled chip
#             showed 31% trial spread where round 4 saw ~1%; the median
#             needs more samples to stay put on a noisy day
GUARD_ITERS = 10
GUARD_TOL = 2e-2
HOST_BLOCK = 8192
# Quiet-box anchor: 0.40 ms/iter (~1350 Mpoints/s) — the honest median
# for the bf16 single-HBM-read stats pass, re-recorded in round 4 after
# the old 0.29 ms anchor was shown to exceed the chip's physical
# bandwidth (doc/benchmarks.md "Round-4 correction").  ROOFLINE_MS is
# the hard physical floor: 268 MB read / 814 GB/s measured HBM rate —
# any reading faster than it is by definition a mis-measurement.
ANCHOR_MS_PER_ITER = 0.40
ROOFLINE_MS_PER_ITER = 0.33
ANCHOR_TOL = 0.20
assert N % HOST_BLOCK == 0, "host baseline drops remainder rows otherwise"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def sched_gains(per_size: dict) -> dict:
    """Per-size best-schedule-vs-static speedup from a collectives
    sizes table: ``{size: {"static": x, "best": name, "best_MBps": y,
    "speedup": y/x}}`` over the schedule columns only."""
    non_sched = {"static", "async", "bucketed"}
    gains = {}
    for size, row in per_size.items():
        base = row.get("static")
        cand = {k: v for k, v in row.items() if k not in non_sched}
        if not base or not cand:
            continue
        best = max(cand, key=cand.get)
        gains[size] = {"static_MBps": base, "best": best,
                       "best_MBps": cand[best],
                       "speedup": round(cand[best] / base, 3)}
    return gains


#: per-link egress budget (MB/s) for the codec A/B passes: roughly a
#: shared 10 Gbps NIC across a 4-rank host — the constrained cross-host
#: regime the quantized codecs target (see BENCH_codec.json "regime")
CODEC_LINK_MBPS = "40"


def run_collectives(args) -> None:
    """``--suite collectives``: 4-rank local pysocket microbench.

    Two launches: a flat-topology pass measuring every applicable
    schedule (tree/ring/halving/swing + static/async/bucketed) per
    payload size, and a pod-shape pass (RABIT_TRACKER_GROUPS=0,0,1,1 —
    two simulated hosts) adding the hierarchical schedule.  Prints TWO
    JSON lines: the headline summary (stream speedup + the best
    schedule-vs-static gains per regime) and the schema-stamped
    per-size MB/s detail (doc/performance.md).  ``--tune-dir`` persists
    the flat pass's winners as the rabit_sched=auto tuning cache."""
    import os
    import tempfile

    from rabit_tpu.tracker.launch_local import launch

    def one_pass(td: str, tag: str, groups: str | None,
                 extra_env: dict | None = None,
                 sizes: str | None = None,
                 tune: bool = False, nworkers: int = 4,
                 pipe_depths: str | None = None,
                 repeat: int | None = None,
                 trace_ab: bool = False,
                 kernel_ab: bool = False) -> dict:
        out = os.path.join(td, f"collectives_{tag}.json")
        cmd = [sys.executable, "-m",
               "rabit_tpu.tools.collectives_bench", out]
        if sizes or args.sizes:
            cmd += ["--sizes", sizes or args.sizes]
        if args.tune_dir and tune:
            cmd += ["--tune-dir", args.tune_dir]
        if pipe_depths:
            cmd += ["--pipe-depths", pipe_depths]
        if trace_ab:
            cmd += ["--trace-ab"]
        if kernel_ab:
            cmd += ["--kernel-ab"]
        if repeat:
            cmd += ["--repeat", str(repeat)]
        # The tracker runs in-process, so the group override must ride
        # the launcher's own environment, not just the workers'.
        saved = os.environ.get("RABIT_TRACKER_GROUPS")
        try:
            if groups is not None:
                os.environ["RABIT_TRACKER_GROUPS"] = groups
            else:
                os.environ.pop("RABIT_TRACKER_GROUPS", None)
            env = {"RABIT_ENGINE": "pysocket"}
            env.update(extra_env or {})
            code = launch(nworkers, cmd, extra_env=env)
        finally:
            if saved is None:
                os.environ.pop("RABIT_TRACKER_GROUPS", None)
            else:
                os.environ["RABIT_TRACKER_GROUPS"] = saved
        if code != 0:
            raise RuntimeError(
                f"collectives bench job ({tag}) failed (exit {code})")
        with open(out) as f:
            return json.load(f)

    with tempfile.TemporaryDirectory() as td:
        # Only passes that explicitly opt in persist tuner rows: the
        # flat world-4 pass (the flagship cache) and the shm transport
        # pass (its allreduce@shm rows) — never the pod/obs/tcp_t
        # passes, whose topologies or world sizes would pollute it.
        flat = one_pass(td, "flat", None, tune=True)
        pod = one_pass(td, "pod", "0,0,1,1")
        # Obs-overhead row: the SAME headline stream with the full live
        # telemetry plane armed (per-op metrics + spans + streaming
        # flush frames on the heartbeat channel).  The sizes ladder is
        # truncated — the stream measurement is the comparison point.
        obs_pass = one_pass(td, "obs", None, sizes="64KB",
                            extra_env={"RABIT_OBS": "1",
                                       "RABIT_OBS_FLUSH_SEC": "0.5"})
        # Trace-armed row: the SAME stream with causal hop tracing on
        # top of the live plane, at the default 1-in-64 op sampling
        # (rabit_trace_sample) the tracing ships with.  Its budget is
        # the same <=3% as the bare live plane — doc/observability.md
        # "Causal tracing & postmortem".  --trace-ab makes the budget
        # measurement a PAIRED in-run A/B (sampling toggled between
        # interleaved trials): cross-launch comparisons on an
        # oversubscribed box jitter by tens of percent of baseline,
        # which would drown a 3% claim either direction.
        from rabit_tpu.obs import DEFAULT_TRACE_SAMPLE
        trace_pass = one_pass(
            td, "traceobs", None, sizes="64KB",
            extra_env={"RABIT_OBS": "1", "RABIT_OBS_FLUSH_SEC": "0.5",
                       "RABIT_TRACE_SAMPLE": str(DEFAULT_TRACE_SAMPLE)},
            trace_ab=True, repeat=5)
        # Transport dimension (doc/benchmarks.md "shm vs tcp"): a
        # same-host world over loopback TCP vs the shm ring transport,
        # on the small-payload ladder where a serving workload lives.
        # World 2 on purpose: it measures the LINK (one hop, no
        # scheduler fan-in) and stays stable on oversubscribed CI boxes
        # where 4 ranks on 2 cores turn the comparison into scheduler
        # noise.  The shm pass also persists its winners under
        # --tune-dir, keyed allreduce@shm so auto picks never bleed
        # across transports (sched/tuner.py table_kind).
        tsizes = "1KB,4KB,16KB,64KB,256KB"
        tcp_t = one_pass(td, "tcp", None, sizes=tsizes, nworkers=2)
        shm_t = one_pass(td, "shm", None, sizes=tsizes,
                         extra_env={"RABIT_TRANSPORT": "shm"},
                         tune=True, nworkers=2)
        # Codec dimension (doc/performance.md "Quantized wire codecs"):
        # world 4 on the bandwidth-bound 256KB-4MB ladder, full-width
        # vs bf16 vs block-scaled int8 — ALL measured under the same
        # rabit_link_mbps egress pacer, because the codecs target
        # constrained cross-host links (EQuARX's DCN regime) and this
        # box's loopback runs at memory speed, where no compression can
        # pay for its compute.  The f32 paced pass never persists tuner
        # rows (it would clobber the flat pass's real loopback
        # winners); the codec passes persist theirs under --tune-dir
        # keyed allreduce+bf16 / allreduce+int8 (sched/tuner.py
        # table_kind) so auto picks never bleed across wire formats
        # whose crossovers differ 2-4x in real bytes.
        csizes = "256KB,1MB,4MB"
        paced = {"RABIT_LINK_MBPS": CODEC_LINK_MBPS}
        # Pipeline dimension (doc/performance.md "Hop pipelining"):
        # the f32 and int8 paced passes ALSO time ring/halving/
        # bucketed with the hop-pipeline depth forced to 1 (the legacy
        # serial loop), 2 and 4 — interleaved INSIDE the run, so the
        # depth A/B is immune to the cross-launch box noise that can
        # easily exceed the overlap win.  The unsuffixed columns (and
        # hence the codec rows and the tuner rows persisted under
        # --tune-dir) ride the default depth, i.e. pipelined timings.
        pdepths = "1,2,4"
        none_c = one_pass(td, "f32paced", None, sizes=csizes,
                          extra_env=dict(paced), pipe_depths=pdepths,
                          repeat=5)
        bf16_c = one_pass(td, "bf16", None, sizes=csizes, tune=True,
                          extra_env={"RABIT_WIRE_CODEC": "bf16", **paced})
        int8_c = one_pass(td, "int8", None, sizes=csizes, tune=True,
                          extra_env={"RABIT_WIRE_CODEC": "int8", **paced},
                          pipe_depths=pdepths, repeat=5)
        # fp8 row (codec/fp8.py): same paced regime and same honest
        # logical-MBps accounting as int8 — the wire carries 1 byte per
        # element plus per-block scales either way, but fp8's error is
        # bounded relative to the VALUE, not the block absmax.
        fp8_c = one_pass(td, "fp8", None, sizes=csizes, tune=True,
                         extra_env={"RABIT_WIRE_CODEC": "fp8e4m3",
                                    **paced})
        # Compiled-kernel A/B passes (codec/kernel.py): UNPACED on
        # purpose — under the 40 MB/s egress budget the wire dominates
        # and any codec-compute win hides behind the pacer, so the
        # honest regime for the kernel claim is loopback at memory
        # speed where the hop math IS the bottleneck.  The A/B itself
        # is paired in-run (kernel bound vs unbound between interleaved
        # trials, --kernel-ab) for the same reason --trace-ab exists:
        # cross-launch jitter on a shared box can exceed the win.  A
        # box without the built library records a skip, never a fake
        # 1.0x row.
        int8_k = one_pass(td, "int8kern", None, sizes="256KB",
                          extra_env={"RABIT_WIRE_CODEC": "int8"},
                          kernel_ab=True, repeat=5)
        fp8_k = one_pass(td, "fp8kern", None, sizes="256KB",
                         extra_env={"RABIT_WIRE_CODEC": "fp8e4m3"},
                         kernel_ab=True, repeat=5)
    stream = flat["stream"]
    obs_stream = obs_pass["stream"]

    # -- codec rows: per (schedule-path, size), MB/s of LOGICAL payload
    # -- moved — the win is real wall-clock, not an accounting trick --
    codec_paths = ("ring", "halving", "bucketed")
    codec_rows: dict[str, dict] = {}
    for size in none_c["sizes"]:
        for path_name in codec_paths:
            base = none_c["sizes"][size].get(path_name)
            row = {"f32_MBps": base}
            for label, res in (("bf16", bf16_c), ("int8", int8_c),
                               ("fp8e4m3", fp8_c)):
                got = res["sizes"].get(size, {}).get(path_name)
                if base and got:
                    row[f"{label}_MBps"] = got
                    row[f"{label}_speedup"] = round(got / base, 3)
            if base:
                codec_rows[f"{path_name}@{size}"] = row
    int8_gains = [r["int8_speedup"] for r in codec_rows.values()
                  if "int8_speedup" in r]
    fp8_gains = [r["fp8e4m3_speedup"] for r in codec_rows.values()
                 if "fp8e4m3_speedup" in r]

    def kernel_ab_row(res: dict) -> dict:
        s = res["stream"]
        if "kernel_speedup" not in s:
            return {"skipped": s.get("kernel_ab_skipped", "no A/B cells")}
        return {"native_MBps": s["blocking_MBps_native"],
                "numpy_MBps": s["blocking_MBps_numpy"],
                "speedup": s["kernel_speedup"]}

    kernel_ab = {
        "regime": "64 x 256KB blocking stream, world 4, UNPACED "
                  "loopback (the compute-bound regime — under the "
                  "egress pacer the wire hides any codec-compute win), "
                  "compiled hop kernel bound vs unbound between "
                  "interleaved trials in ONE run (--kernel-ab)",
        "int8": kernel_ab_row(int8_k),
        "fp8e4m3": kernel_ab_row(fp8_k),
    }
    codec_summary = {
        "metric": "codec_speedup_bandwidth",
        "value": round(max(int8_gains), 3) if int8_gains else 0.0,
        "min": round(min(int8_gains), 3) if int8_gains else 0.0,
        "unit": "x",
        "world": flat["world"],
        "link_mbps": float(CODEC_LINK_MBPS),
        "regime": ">=256KB, world 4, ring/halving/bucketed paths, "
                  f"int8 block-scaled wire vs f32, both under a "
                  f"{CODEC_LINK_MBPS} MB/s per-link egress budget "
                  "(rabit_link_mbps)",
        "value_fp8e4m3": round(max(fp8_gains), 3) if fp8_gains else 0.0,
        "rows": codec_rows,
        "stream_int8_MBps": int8_c["stream"]["blocking_MBps"],
        "stream_bf16_MBps": bf16_c["stream"]["blocking_MBps"],
        "stream_fp8e4m3_MBps": fp8_c["stream"]["blocking_MBps"],
        "stream_f32_MBps": none_c["stream"]["blocking_MBps"],
        "kernel_ab": kernel_ab,
    }
    with open(args.codec_json, "w") as f:
        json.dump(codec_summary, f, indent=2, sort_keys=True)
    log(f"bench: wrote codec rows to {args.codec_json}")

    # -- pipeline rows: depth 1 (serial) vs 2 (default) vs 4, per
    # -- (schedule path, size), f32 and int8 — MB/s of LOGICAL payload,
    # -- so the speedup is wall-clock overlap, not accounting ----------
    pipe_paths = ("ring", "halving", "bucketed")
    pipe_rows: dict[str, dict] = {}
    for size in none_c["sizes"]:
        for path_name in pipe_paths:
            row: dict = {}
            for label, res in (("f32", none_c), ("int8", int8_c)):
                cols = res["sizes"].get(size, {})
                base = cols.get(f"{path_name}_d1")
                for depth in (1, 2, 4):
                    got = cols.get(f"{path_name}_d{depth}")
                    if not got:
                        continue
                    row[f"{label}_d{depth}_MBps"] = got
                    if depth > 1 and base:
                        row[f"{label}_d{depth}_speedup"] = round(
                            got / base, 3)
            if row:
                pipe_rows[f"{path_name}@{size}"] = row
    big_gains = [r["int8_d2_speedup"] for k, r in pipe_rows.items()
                 if "int8_d2_speedup" in r
                 and int(k.split("@")[1]) >= (1 << 20)]
    all_gains = [r[k2] for r in pipe_rows.values() for k2 in
                 ("f32_d2_speedup", "int8_d2_speedup") if k2 in r]
    int8_4mb = codec_rows.get("bucketed@4194304", {}).get("int8_speedup")
    # The bench VERIFIER: the cells this PR exists to hold fail LOUDLY
    # (stderr + a regressions list in the JSON) instead of silently
    # drifting: the paced int8 bucketed@4MB win over f32 must stay
    # >= 1.2x, and NO depth-2 cell may fall below the no-regression
    # floor (the pipeline must never cost bandwidth where it has
    # nothing to hide).  The 1.3x overlap target is reported as
    # target_met rather than hard-failed: on a 2-core box the serial
    # baseline already self-overlaps up to the pacer's burst (the
    # kernel-socket-buffer analogue) and the codec math contends for
    # the same cores as the wire pumps, which bounds the honestly
    # measurable headroom.
    regressions = []
    if int8_4mb is None or int8_4mb < 1.2:
        regressions.append(
            f"int8 bucketed@4MB vs f32 = {int8_4mb} (floor 1.2x)")
    if not all_gains:
        # A verifier with nothing to verify must fail, not pass: no
        # depth-suffixed cells means the --pipe-depths plumbing (or
        # the ring_dN/bucketed_dN labels) silently broke.
        regressions.append("no depth-speedup cells measured — the "
                           "--pipe-depths plumbing is broken")
    if all_gains and min(all_gains) < 0.75:
        # 0.75, not ~1.0: many cells run the identical serial path at
        # every depth (hops under two pipeline-chunk floors), so their
        # ratio is pure box noise — the tripwire exists for real
        # breakage (a stalled window, a pathological chunk size), not
        # for scheduler jitter on a 2-core host.
        regressions.append(
            f"worst depth-2-vs-serial cell = {min(all_gains)} "
            "(no-regression floor 0.75x)")
    for what in regressions:
        log(f"bench: PIPELINE REGRESSION: {what}")
    pipeline_summary = {
        "metric": "pipeline_speedup_bandwidth",
        "value": round(max(big_gains), 3) if big_gains else 0.0,
        "min": round(min(big_gains), 3) if big_gains else 0.0,
        "unit": "x",
        "world": flat["world"],
        "link_mbps": float(CODEC_LINK_MBPS),
        "depth_default": none_c.get("pipeline_depth", 2),
        "regime": ">=1MB, world 4, ring/halving/bucketed paths, int8 "
                  "wire: depth-2 pipelined hops vs the depth-1 serial "
                  f"loop, all under a {CODEC_LINK_MBPS} MB/s per-link "
                  "egress budget (rabit_link_mbps); f32 rows ride "
                  "along to show the classic wire is compute-light "
                  "here (its merge has little to hide)",
        "int8_bucketed_4MB_speedup": int8_4mb,
        "all_depth2_speedups_min": (round(min(all_gains), 3)
                                    if all_gains else 0.0),
        "target_speedup": 1.3,
        "target_met": bool(big_gains) and max(big_gains) >= 1.3,
        "rows": pipe_rows,
        # The native-kernel paired A/B rides the pipeline rerun: both
        # claims are about the same hop loop (overlap hides the merge
        # compute the kernel shrinks), so they are recorded together.
        "kernel_ab": kernel_ab,
        "regressions": regressions,
        "verified": not regressions,
    }
    with open(args.pipeline_json, "w") as f:
        json.dump(pipeline_summary, f, indent=2, sort_keys=True)
    log(f"bench: wrote pipeline rows to {args.pipeline_json}")

    # -- shm-vs-tcp rows (the `static` column is the real dispatch) --
    transport_rows = {}
    for size in tcp_t["sizes"]:
        base = tcp_t["sizes"][size].get("static")
        shm = shm_t["sizes"].get(size, {}).get("static")
        if base and shm:
            transport_rows[size] = {
                "tcp_MBps": base, "shm_MBps": shm,
                "speedup": round(shm / base, 3)}
    small = [r["speedup"] for s, r in transport_rows.items()
             if int(s) <= (64 << 10)]
    transport_summary = {
        "metric": "shm_vs_tcp_small_payload_speedup",
        "value": round(min(small), 3) if small else 0.0,
        "best": round(max(small), 3) if small else 0.0,
        "unit": "x",
        "world": tcp_t["world"],
        "regime": "<=64KB, same-host world 2, static dispatch",
        "sizes": transport_rows,
        "stream_shm_MBps": shm_t["stream"]["blocking_MBps"],
        "stream_tcp_MBps": tcp_t["stream"]["blocking_MBps"],
    }
    with open(args.transport_json, "w") as f:
        json.dump(transport_summary, f, indent=2, sort_keys=True)
    log(f"bench: wrote transport rows to {args.transport_json}")

    def overhead_pct(off: float, on: float) -> float:
        return round(100.0 * (1.0 - on / off), 2) if off else 0.0

    obs_overhead = {
        "blocking_pct": overhead_pct(stream["blocking_MBps"],
                                     obs_stream["blocking_MBps"]),
        "fused_pct": overhead_pct(stream["fused_MBps"],
                                  obs_stream["fused_MBps"]),
        "blocking_MBps_obs": obs_stream["blocking_MBps"],
        "fused_MBps_obs": obs_stream["fused_MBps"],
    }
    trace_stream = trace_pass["stream"]
    # The budget is verified on the PAIRED in-run A/B (same process,
    # sockets and stream; sampling toggled between interleaved trials)
    # — the cross-launch rows below it are recorded for context but
    # inherit the box's full baseline jitter, so they are NOT the
    # claim.  Honest accounting: both live in the JSON, a blown budget
    # is LOUD on stderr, nothing is clipped.
    trace_overhead = {
        "blocking_pct": overhead_pct(
            trace_stream["blocking_MBps_untraced"],
            trace_stream["blocking_MBps_traced"]),
        "blocking_MBps_traced": trace_stream["blocking_MBps_traced"],
        "blocking_MBps_untraced": trace_stream["blocking_MBps_untraced"],
        "trace_sample": trace_stream.get("trace_sample"),
        "vs_flat_blocking_pct": overhead_pct(
            stream["blocking_MBps"], trace_stream["blocking_MBps"]),
        "vs_flat_fused_pct": overhead_pct(
            stream["fused_MBps"], trace_stream["fused_MBps"]),
        "budget_pct": 3.0,
    }
    trace_overhead["verified"] = trace_overhead["blocking_pct"] <= 3.0
    if not trace_overhead["verified"]:
        log("bench: TRACE OVERHEAD BUDGET EXCEEDED: "
            f"{trace_overhead['blocking_pct']}% > 3% "
            "(rabit_trace_sample default, paired in-run A/B)")
    flat_gains = sched_gains(flat["sizes"])
    pod_gains = sched_gains(pod["sizes"])
    best_flat = max((g["speedup"] for g in flat_gains.values()),
                    default=0.0)
    best_pod = max((g["speedup"] for g in pod_gains.values()),
                   default=0.0)
    summary = {
        "metric": "collectives_stream_speedup",
        "value": stream["speedup"],
        "unit": "x",
        "blocking_MBps": stream["blocking_MBps"],
        "fused_MBps": stream["fused_MBps"],
        "stream": f"{stream['ops']} x {stream['payload_bytes']} B sum",
        "sched_speedup_flat": best_flat,
        "sched_speedup_pod": best_pod,
        # worst-case shm-over-tcp speedup in the <=64KB regime (the
        # BENCH_transport.json headline; >1.0 means shm wins everywhere
        # in the small-payload band)
        "transport_speedup_small": transport_summary["value"],
        # best int8-wire-over-f32 speedup on the bandwidth-bound
        # >=256KB ring/halving/bucketed rows (the BENCH_codec.json
        # headline — raw bandwidth bought by the quantized wire)
        "codec_speedup_bandwidth": codec_summary["value"],
        # best depth-2-over-serial hop-pipeline speedup on the paced
        # >=1MB int8 rows (the BENCH_pipeline.json headline — wall
        # clock bought by overlapping merge compute with wire IO)
        "pipeline_speedup_bandwidth": pipeline_summary["value"],
        # compiled-hop-kernel-over-numpy speedup on the UNPACED int8
        # blocking stream, paired in-run A/B (BENCH_codec.json
        # kernel_ab detail); 0.0 records "library not built", never a
        # fake 1.0
        "codec_kernel_speedup": kernel_ab["int8"].get("speedup", 0.0),
        # the live-telemetry tax on the headline stream (the <3% claim
        # in doc/observability.md "Live telemetry"; noisy-box runs can
        # legitimately go slightly negative)
        "obs_overhead_pct": obs_overhead["blocking_pct"],
        # the same stream with hop tracing armed at the default 1-in-64
        # sampling — budgeted <=3% like the bare live plane, verified
        # (trace_overhead.verified in the detail doc)
        "trace_overhead_pct": trace_overhead["blocking_pct"],
        "trace_overhead_verified": trace_overhead["verified"],
    }
    detail = {"suite": "collectives", "schema": flat.get("schema"),
              "host": flat.get("host"), "world": flat["world"],
              "per_size_MBps": flat["sizes"], "stream": stream,
              "sched_gains": flat_gains,
              "obs_overhead": obs_overhead,
              "trace_overhead": trace_overhead,
              "pod": {"groups": pod.get("groups"),
                      "per_size_MBps": pod["sizes"],
                      "sched_gains": pod_gains},
              "transport": transport_summary,
              "codec": codec_summary,
              "pipeline": pipeline_summary}
    if args.json:
        with open(args.json, "w") as f:
            json.dump({**summary, "telemetry": detail,
                       "engine_stats": flat.get("engine_stats", {})},
                      f, indent=2, sort_keys=True)
        log(f"bench: wrote JSON summary to {args.json}")
    print(json.dumps(summary))
    print(json.dumps(detail))


def run_serve_bench(args) -> None:
    """``--suite serve``: requests/s × latency of the serving plane
    (doc/serving.md), steady and under a 2x-capacity open-loop spike.

    A 2-rank fleet with a PINNED capacity (the slow-ms compute seam:
    10 ms/request → 100 req/s/rank) serves bitwise-verified traffic
    from the open-loop generator; the suite records both operating
    points into BENCH_serve.json together with a **verifier** that
    fails (stderr + ``verified: false`` in the JSON) when the shed
    accounting does not close exactly (served + shed + timeout +
    errored == offered) or any reply is bitwise wrong — a shed ledger
    that doesn't balance means requests vanished, which is precisely
    the overload bug the serving plane exists to prevent."""
    import os
    import pathlib
    import shutil
    import subprocess
    import tempfile

    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.tools.loadgen import run_load
    from rabit_tpu.utils.serial import serialize_model

    # Low absolute rates on purpose: the generator shares the box with
    # the fleet (see tools/soak.py run_serve) — the suite's value is
    # the two operating points and the accounting verifier, not a
    # loopback-QPS bragging number.
    fleet, slow_ms, dim = 2, 25.0, 16
    batch_max, queue_max = 4, 16
    capacity = fleet * 1000.0 / slow_ms
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_serve_bench_"))
    model_dir, eps_dir = base / "model", base / "eps"
    store = ckpt_mod.CheckpointStore(str(model_dir), rank=0)
    store.persist(1, fleet, serialize_model(
        {"w": np.random.default_rng(0).standard_normal(dim)}))
    sup = subprocess.Popen(
        [sys.executable, "-m", "rabit_tpu.tools.serve",
         "--model-dir", str(model_dir), "--endpoints-dir", str(eps_dir),
         "--workers", str(fleet), "--slow-ms", str(slow_ms),
         "--sync-sec", "0.5", "--batch-max", str(batch_max),
         "--queue-max", str(queue_max),
         "--stop-file", str(base / "STOP")],
        env=dict(os.environ), stdout=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if eps_dir.is_dir() and len(list(
                    eps_dir.glob("*.json"))) >= fleet:
                break
            if sup.poll() is not None:
                raise RuntimeError(f"serve supervisor exited "
                                   f"{sup.returncode} during startup")
            time.sleep(0.3)
        else:
            raise RuntimeError("serving fleet never came up")
        log(f"bench serve: fleet of {fleet} up, pinned capacity "
            f"{capacity:.0f} req/s")
        steady = run_load(str(eps_dir), None, rate=capacity * 0.5,
                          duration=8, deadline_ms=2000, dim=dim,
                          verify_dir=str(model_dir))
        log(f"bench serve: steady {steady['achieved_req_s']:.1f} "
            f"req/s served, p99 "
            f"{steady['latency_ok_sec']['p99'] * 1e3:.1f} ms")
        spike = run_load(str(eps_dir), None, rate=capacity * 2,
                         duration=8, deadline_ms=500, dim=dim,
                         outstanding=128, verify_dir=str(model_dir))
        log(f"bench serve: spike {spike['achieved_req_s']:.1f} req/s "
            f"served of {spike['rate_req_s']:.0f} offered, "
            f"{spike['shed']} shed, p99 "
            f"{spike['latency_ok_sec']['p99'] * 1e3:.1f} ms")
        (base / "STOP").touch()
        sup.wait(timeout=30)
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait()
        shutil.rmtree(base, ignore_errors=True)

    failures = []
    for tag, rep in (("steady", steady), ("spike", spike)):
        if not rep["accounting_ok"]:
            failures.append(
                f"{tag}: shed accounting mismatch — "
                f"ok {rep['ok']} + shed {rep['shed']} + timeout "
                f"{rep['timeout']} + error {rep['error']} != offered "
                f"{rep['offered']}")
        if rep["wrong"]:
            failures.append(f"{tag}: {rep['wrong']} bitwise-wrong "
                            "replies")
    if not spike["shed"]:
        failures.append("spike: a 2x-capacity spike shed nothing — "
                        "the admission gate is not engaging")
    for f in failures:
        log(f"bench serve VERIFIER FAILED: {f}")
    summary = {
        "suite": "serve", "fleet": fleet,
        "capacity_req_s": capacity, "slow_ms": slow_ms,
        "requests_per_sec_steady": steady["achieved_req_s"],
        "p99_ms_steady": steady["latency_ok_sec"]["p99"] * 1e3,
        "requests_per_sec_spike": spike["achieved_req_s"],
        "p99_ms_spike": spike["latency_ok_sec"]["p99"] * 1e3,
        "spike_shed_fraction": (spike["shed"] / spike["offered"]
                                if spike["offered"] else 0.0),
        "verified": not failures,
        "verifier_failures": failures,
        "steady": steady, "spike": spike,
    }
    out = args.serve_json
    with open(out, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    log(f"bench serve: wrote {out} (verified={not failures})")
    print(json.dumps({k: summary[k] for k in
                      ("suite", "fleet", "capacity_req_s",
                       "requests_per_sec_steady", "p99_ms_steady",
                       "requests_per_sec_spike", "p99_ms_spike",
                       "spike_shed_fraction", "verified")}))


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="rabit_tpu benchmark harness")
    ap.add_argument("--json", default=None, metavar="OUT.json",
                    help="also write the summary + aggregated telemetry "
                         "(per-candidate table, engine obs snapshot) to "
                         "this file")
    ap.add_argument("--suite", default="kmeans",
                    choices=["kmeans", "collectives", "serve"],
                    help="kmeans (default): the flagship device workload; "
                         "collectives: 4-rank host-path microbench "
                         "(per-schedule MB/s + stream speedup); "
                         "serve: serving-plane requests/s × latency, "
                         "steady + 2x-capacity spike, with the "
                         "shed-accounting verifier (BENCH_serve.json)")
    ap.add_argument("--sizes", default=None,
                    help="collectives suite: comma-separated payload "
                         "sizes overriding the default ladder "
                         "(byte suffixes OK, e.g. 4KB,64KB,1MB)")
    ap.add_argument("--tune-dir", default=None,
                    help="collectives suite: persist the measured "
                         "per-size schedule winners as the "
                         "rabit_sched=auto tuning cache here (the shm "
                         "transport pass adds allreduce@shm rows; the "
                         "codec passes add allreduce+bf16 / "
                         "allreduce+int8 rows)")
    ap.add_argument("--transport-json", default="BENCH_transport.json",
                    metavar="OUT.json",
                    help="collectives suite: where the shm-vs-tcp "
                         "small-payload rows land")
    ap.add_argument("--codec-json", default="BENCH_codec.json",
                    metavar="OUT.json",
                    help="collectives suite: where the quantized-wire "
                         "(bf16/int8/fp8 vs f32) bandwidth rows and "
                         "the paired compiled-kernel A/B land")
    ap.add_argument("--pipeline-json", default="BENCH_pipeline.json",
                    metavar="OUT.json",
                    help="collectives suite: where the hop-pipeline "
                         "depth (1 vs 2 vs 4, f32/int8, paced) rows "
                         "land, with the cell-floor verifier verdict")
    ap.add_argument("--serve-json", default="BENCH_serve.json",
                    metavar="OUT.json",
                    help="serve suite: where the requests/s × latency "
                         "rows and the shed-accounting verifier "
                         "verdict land")
    args = ap.parse_args(argv)

    if args.suite == "collectives":
        run_collectives(args)
        return
    if args.suite == "serve":
        run_serve_bench(args)
        return

    import jax

    import rabit_tpu
    from rabit_tpu.learn import kmeans

    rabit_tpu.init(rabit_engine="empty")

    rng = np.random.default_rng(0)
    findex = rng.integers(0, D, (N, NNZ)).astype(np.int32)
    fvalue = rng.standard_normal((N, NNZ)).astype(np.float32)
    cent0 = rng.standard_normal((K, D)).astype(np.float32)

    # densify once on host (scatter is centroid-independent; the app does
    # this staging on device via prepare_shard)
    dense = np.zeros((N, D), np.float32)
    rows = np.arange(N)[:, None]
    np.add.at(dense, (rows, findex), fvalue)
    valid = np.ones(N, np.float32)

    import jax.numpy as jnp

    x_dev = jax.device_put(jnp.asarray(dense))
    v_dev = jax.device_put(jnp.asarray(valid))
    c_dev = jax.device_put(jnp.asarray(cent0))

    def chain(iters: int, use_pallas: bool, dtype: str):
        return kmeans.device_iterations(c_dev, x_dev, v_dev, iters,
                                        use_pallas=use_pallas,
                                        compute_dtype=dtype)

    oracle = np.asarray(chain(GUARD_ITERS, False, "float32"),
                        dtype=np.float32)
    oracle_norm = np.linalg.norm(oracle)

    def guard_err(use_pallas: bool, dtype: str) -> float:
        got = np.asarray(chain(GUARD_ITERS, use_pallas, dtype),
                         dtype=np.float32)
        return float(np.linalg.norm(got - oracle) / oracle_norm)

    on_tpu = jax.default_backend() == "tpu"
    candidates = [(False, "float32")]
    if on_tpu:
        candidates += [(False, "bfloat16"), (True, "float32"),
                       (True, "bfloat16")]

    # Guard + compile phase: weed out broken/inaccurate candidates,
    # reporting each verdict; compile both chain lengths for survivors so
    # the timed trials below measure execution only.
    alive: list[tuple[bool, str]] = []
    for use_pallas, dtype in candidates:
        name = f"pallas={use_pallas},dtype={dtype}"
        try:
            if (use_pallas, dtype) != (False, "float32"):
                # (False, "float32") IS the oracle — tautological guard
                err = guard_err(use_pallas, dtype)
                if err >= GUARD_TOL:
                    log(f"bench: DISCARD {name}: numerics guard "
                        f"rel_err={err:.3g} >= {GUARD_TOL}")
                    continue
            np.asarray(chain(ITERS_SHORT, use_pallas, dtype))
            np.asarray(chain(ITERS_LONG, use_pallas, dtype))
            alive.append((use_pallas, dtype))
        except Exception as exc:  # noqa: BLE001 — report, never mask
            log(f"bench: DISCARD {name}: {type(exc).__name__}: {exc}")
    if not alive:
        raise RuntimeError("every bench candidate failed to run")

    # Interleaved difference-timing trials: one full pass over the live
    # candidates per trial, so transient load perturbs all of them.
    # Non-positive differences (a load stall during the short run) and
    # transient run failures are logged and dropped, never averaged in.
    samples: dict[tuple[bool, str], list[float]] = {c: [] for c in alive}
    for trial in range(TRIALS):
        for use_pallas, dtype in alive:
            name = f"pallas={use_pallas},dtype={dtype}"
            try:
                t0 = time.perf_counter()
                np.asarray(chain(ITERS_SHORT, use_pallas, dtype))
                t_short = time.perf_counter() - t0
                t0 = time.perf_counter()
                np.asarray(chain(ITERS_LONG, use_pallas, dtype))
                t_long = time.perf_counter() - t0
            except Exception as exc:  # noqa: BLE001 — report, never mask
                log(f"bench: trial {trial} {name} FAILED: "
                    f"{type(exc).__name__}: {exc}")
                continue
            dt = (t_long - t_short) / (ITERS_LONG - ITERS_SHORT)
            if dt <= 0:
                log(f"bench: trial {trial} {name}: non-positive diff "
                    f"({dt * 1e3:.4f} ms) — load stall, dropped")
                continue
            samples[(use_pallas, dtype)].append(dt)
    for cand in [c for c, xs in samples.items() if len(xs) < 2]:
        use_pallas, dtype = cand
        log(f"bench: DISCARD pallas={use_pallas},dtype={dtype}: fewer "
            "than 2 valid trials")
        del samples[cand]
    if not samples:
        raise RuntimeError("no bench candidate produced valid timings")

    def spread_pct(xs: list[float]) -> float:
        med = statistics.median(xs)
        return 100.0 * (max(xs) - min(xs)) / med if med > 0 else 0.0

    log("bench: candidate table (per-iter seconds over "
        f"{TRIALS} interleaved trials):")
    best = None
    for cand, xs in samples.items():
        med = statistics.median(xs)
        use_pallas, dtype = cand
        log(f"bench:   pallas={use_pallas!s:5} dtype={dtype:8} "
            f"median={med * 1e3:.4f} ms  min={min(xs) * 1e3:.4f}  "
            f"max={max(xs) * 1e3:.4f}  spread={spread_pct(xs):.1f}%")
        if best is None or med < best[1]:
            best = (cand, med, xs)
    assert best is not None
    (win_pallas, win_dtype), dt_dev, win_samples = best
    log(f"bench: winner pallas={win_pallas},dtype={win_dtype}")

    # Anchor cross-check (TPU only — the anchor is a chip measurement).
    # The roofline scales with the winner's HBM footprint (one read of x
    # in its compute dtype); the recorded 0.40 ms anchor is specific to
    # the expected winner (pallas + bfloat16), so a different winner is
    # itself flagged rather than compared against the wrong constant.
    suspect = False
    if on_tpu:
        itemsize = 2 if win_dtype == "bfloat16" else 4
        floor_ms = ROOFLINE_MS_PER_ITER * itemsize / 2
        if dt_dev * 1e3 < floor_ms * 0.98:
            suspect = True
            log(f"bench: MEASUREMENT SUSPECT: winner {dt_dev * 1e3:.4f} "
                f"ms/iter is below the {floor_ms:.2f} ms physical HBM "
                "floor — this reading is impossible; the timing is "
                "broken (doc/benchmarks.md 'Round-4 correction')")
        elif (win_pallas, win_dtype) != (True, "bfloat16"):
            suspect = True
            log(f"bench: MEASUREMENT SUSPECT: expected winner "
                "pallas=True,dtype=bfloat16 was discarded — the recorded "
                "anchor does not apply; investigate why it lost or failed")
        else:
            dev = dt_dev * 1e3 / ANCHOR_MS_PER_ITER - 1.0
            if abs(dev) > ANCHOR_TOL:
                suspect = True
                log(f"bench: MEASUREMENT SUSPECT: winner "
                    f"{dt_dev * 1e3:.4f} ms/iter deviates {dev * 100:+.1f}% "
                    f"from the recorded {ANCHOR_MS_PER_ITER} ms/iter anchor "
                    "(doc/benchmarks.md) — box load or chip change?")

    # host baseline: the reference's design point (CPU compute + CPU
    # reducer, kmeans.cc:126-140), vectorized numpy, one iteration
    def host_pass(model):
        cn = model / np.linalg.norm(model, axis=1, keepdims=True)
        stats = np.zeros((K, D + 1), np.float32)
        for b in range(N // HOST_BLOCK):
            sl = slice(b * HOST_BLOCK, (b + 1) * HOST_BLOCK)
            xb = dense[sl]
            assign = (xb @ cn.T).argmax(axis=1)
            oh = np.zeros((HOST_BLOCK, K), np.float32)
            oh[np.arange(HOST_BLOCK), assign] = 1.0
            ext = np.concatenate([xb, np.ones((HOST_BLOCK, 1), np.float32)],
                                 axis=1)
            stats += oh.T @ ext
        return stats

    host_pass(cent0)  # warm caches
    t0 = time.perf_counter()
    host_pass(cent0)
    dt_host = time.perf_counter() - t0

    mpts_dev = N / dt_dev / 1e6
    mpts_host = N / dt_host / 1e6
    summary = {
        "metric": "kmeans_device_iteration_throughput",
        "value": round(mpts_dev, 3),
        "unit": "Mpoints/s",
        "vs_baseline": round(mpts_dev / mpts_host, 3),
        "spread_pct": round(spread_pct(win_samples), 1),
        "suspect": suspect,
    }
    if args.json:
        # Aggregated telemetry rides along so a recorded BENCH entry
        # carries its own evidence: the full interleaved candidate
        # table, the winner, and the engine's obs snapshot.
        from rabit_tpu import engine as _em

        telemetry = {
            "backend": jax.default_backend(),
            "winner": {"pallas": win_pallas, "dtype": win_dtype,
                       "ms_per_iter": round(dt_dev * 1e3, 4)},
            "candidates": {
                f"pallas={up},dtype={dt}": {
                    "median_ms": round(statistics.median(xs) * 1e3, 4),
                    "min_ms": round(min(xs) * 1e3, 4),
                    "max_ms": round(max(xs) * 1e3, 4),
                    "trials": len(xs),
                } for (up, dt), xs in samples.items()},
            "host_baseline_ms": round(dt_host * 1e3, 4),
            "engine_stats": _em.get_engine().stats(),
        }
        with open(args.json, "w") as f:
            json.dump({**summary, "telemetry": telemetry}, f, indent=2,
                      sort_keys=True)
        log(f"bench: wrote JSON summary to {args.json}")
    rabit_tpu.finalize()
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
