"""Benchmark harness — prints ONE JSON line.

Benchmarks the flagship workload: full k-means iterations (assign +
accumulate + recompute, the per-iteration work of the reference app,
reference: rabit-learn/kmeans/kmeans.cc:121-157).  The framework path is
``kmeans.device_iterations`` — the device-resident chained loop the app
uses via ``kmeans.run(device_chain=...)`` — with the fused Pallas stats
kernel (rabit_tpu/ops/kmeans_kernel.py, single HBM read per iteration,
bf16 compute / f32 accumulate) or the XLA two-matmul pass, whichever is
faster on the local chip.  The baseline is the reference's design point
— host-side compute feeding the collective — implemented as strong
*vectorized* numpy (already far faster than the reference's actual
per-row C++ loop, so vs_baseline is conservative).

Timing method: the axon-tunneled TPU adds a fixed ~95 ms round trip to
every fetched execution, so a single chained run over-reports per-iter
cost.  We time a short (ITERS_SHORT) and a long (ITERS_LONG) chain of
the same recurrent loop and take (T_long - T_short) / (ITERS_LONG -
ITERS_SHORT), which cancels the fixed cost exactly; the loop is a true
recurrence (centroids feed back), so XLA cannot hoist the body.

A numerics guard runs the candidate variant against the float32 XLA
oracle for GUARD_ITERS iterations and requires the final centroids to
match within GUARD_TOL relative Frobenius error; variants that fail are
discarded.

Metric: million points/sec through one full k-means iteration
(k=64 clusters, d=256 features, 512k points densified from 32-nnz rows).
"""
from __future__ import annotations

import json
import time

import numpy as np

N, D, K, NNZ = 1 << 19, 256, 64, 32
ITERS_SHORT, ITERS_LONG = 50, 500
GUARD_ITERS = 10
GUARD_TOL = 2e-2
HOST_BLOCK = 8192
assert N % HOST_BLOCK == 0, "host baseline drops remainder rows otherwise"


def main() -> None:
    import jax
    import jax.numpy as jnp

    import rabit_tpu
    from rabit_tpu.learn import kmeans

    rabit_tpu.init(rabit_engine="empty")

    rng = np.random.default_rng(0)
    findex = rng.integers(0, D, (N, NNZ)).astype(np.int32)
    fvalue = rng.standard_normal((N, NNZ)).astype(np.float32)
    cent0 = rng.standard_normal((K, D)).astype(np.float32)

    # densify once on host (scatter is centroid-independent; the app does
    # this staging on device via prepare_shard)
    dense = np.zeros((N, D), np.float32)
    rows = np.arange(N)[:, None]
    np.add.at(dense, (rows, findex), fvalue)
    valid = np.ones(N, np.float32)

    x_dev = jax.device_put(jnp.asarray(dense))
    v_dev = jax.device_put(jnp.asarray(valid))
    c_dev = jax.device_put(jnp.asarray(cent0))

    def chain(iters: int, use_pallas: bool, dtype: str):
        return kmeans.device_iterations(c_dev, x_dev, v_dev, iters,
                                        use_pallas=use_pallas,
                                        compute_dtype=dtype)

    oracle = np.asarray(chain(GUARD_ITERS, False, "float32"),
                        dtype=np.float32)
    oracle_norm = np.linalg.norm(oracle)

    def accurate(use_pallas: bool, dtype: str) -> bool:
        got = np.asarray(chain(GUARD_ITERS, use_pallas, dtype),
                         dtype=np.float32)
        return (np.linalg.norm(got - oracle) / oracle_norm) < GUARD_TOL

    def timed(use_pallas: bool, dtype: str) -> float:
        # warm/compile both chain lengths, then difference-time
        np.asarray(chain(ITERS_SHORT, use_pallas, dtype))
        np.asarray(chain(ITERS_LONG, use_pallas, dtype))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(chain(ITERS_SHORT, use_pallas, dtype))
            t_short = time.perf_counter() - t0
            t0 = time.perf_counter()
            np.asarray(chain(ITERS_LONG, use_pallas, dtype))
            t_long = time.perf_counter() - t0
            best = min(best, (t_long - t_short) / (ITERS_LONG - ITERS_SHORT))
        return best

    on_tpu = jax.default_backend() == "tpu"
    candidates = [(False, "float32")]
    if on_tpu:
        candidates += [(False, "bfloat16"), (True, "float32"),
                       (True, "bfloat16")]
    dt_dev = float("inf")
    for use_pallas, dtype in candidates:
        try:
            # (False, "float32") IS the oracle — skip the tautological guard
            if (use_pallas, dtype) != (False, "float32") \
                    and not accurate(use_pallas, dtype):
                continue
            dt_dev = min(dt_dev, timed(use_pallas, dtype))
        except Exception:
            pass
    if not np.isfinite(dt_dev):
        raise RuntimeError("every bench candidate failed to run")

    # host baseline: the reference's design point (CPU compute + CPU
    # reducer, kmeans.cc:126-140), vectorized numpy, one iteration
    def host_pass(model):
        cn = model / np.linalg.norm(model, axis=1, keepdims=True)
        stats = np.zeros((K, D + 1), np.float32)
        for b in range(N // HOST_BLOCK):
            sl = slice(b * HOST_BLOCK, (b + 1) * HOST_BLOCK)
            xb = dense[sl]
            assign = (xb @ cn.T).argmax(axis=1)
            oh = np.zeros((HOST_BLOCK, K), np.float32)
            oh[np.arange(HOST_BLOCK), assign] = 1.0
            ext = np.concatenate([xb, np.ones((HOST_BLOCK, 1), np.float32)],
                                 axis=1)
            stats += oh.T @ ext
        return stats

    host_pass(cent0)  # warm caches
    t0 = time.perf_counter()
    host_pass(cent0)
    dt_host = time.perf_counter() - t0

    mpts_dev = N / dt_dev / 1e6
    mpts_host = N / dt_host / 1e6
    rabit_tpu.finalize()
    print(json.dumps({
        "metric": "kmeans_device_iteration_throughput",
        "value": round(mpts_dev, 3),
        "unit": "Mpoints/s",
        "vs_baseline": round(mpts_dev / mpts_host, 3),
    }))


if __name__ == "__main__":
    main()
