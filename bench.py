"""Benchmark harness — prints ONE JSON line.

Benchmarks the flagship workload: the distributed k-means cluster-stats
pass (assign + accumulate, the per-iteration compute the reference app
allreduces, reference: rabit-learn/kmeans/kmeans.cc:121-157).  The
framework path runs it as a single jitted XLA program on the accelerator
(scatter-densify + MXU matmuls, rabit_tpu/learn/kmeans.py); the baseline
is the reference's design point — host-side compute feeding the
collective — implemented as strong *vectorized* numpy (already far faster
than the reference's actual per-row C++ loop, so vs_baseline is
conservative).

Metric: million points/sec through one full stats pass (k=64 clusters,
d=256 features, 512k sparse points of 32 nnz each).
"""
from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    import rabit_tpu
    from rabit_tpu.learn import kmeans
    from rabit_tpu.learn.data import SparseMat

    rabit_tpu.init(rabit_engine="empty")

    n, d, k, nnz_per_row = 1 << 19, 256, 64, 32
    rng = np.random.default_rng(0)
    findex = rng.integers(0, d, (n, nnz_per_row)).astype(np.int32)
    fvalue = rng.standard_normal((n, nnz_per_row)).astype(np.float32)
    mat = SparseMat(
        indptr=np.arange(0, n * nnz_per_row + 1, nnz_per_row, np.int64),
        findex=findex.reshape(-1),
        fvalue=fvalue.reshape(-1),
        labels=np.zeros(n, np.float32),
        feat_dim=d,
    )
    model = kmeans.KMeansModel(
        rng.standard_normal((k, d)).astype(np.float32))

    row_block = 8192
    idx, val, _labels, valid = mat.to_ell(pad_index=d, row_block=row_block)
    shard = kmeans.prepare_shard(idx, val, valid, d, row_block)

    def device_pass():
        return kmeans.shard_stats(model, shard)

    device_pass()  # warmup / compile
    t0 = time.perf_counter()
    repeats = 5
    for _ in range(repeats):
        out = device_pass()
    jax.block_until_ready(out) if hasattr(out, "block_until_ready") else None
    dt_dev = (time.perf_counter() - t0) / repeats

    # host baseline: the reference's design point (CPU compute + CPU
    # reducer, kmeans.cc:126-140), vectorized numpy
    scratch = np.zeros((row_block, d + 1), np.float32)

    def host_pass():
        cn = model.centroids / np.linalg.norm(
            model.centroids, axis=1, keepdims=True)
        stats = np.zeros((k, d + 1), np.float32)
        nb = idx.shape[0] // row_block
        rows = np.arange(row_block)[:, None]
        for b in range(nb):
            sl = slice(b * row_block, (b + 1) * row_block)
            scratch[:] = 0.0
            np.add.at(scratch, (rows, idx[sl]), val[sl])
            dense = scratch[:, :d]
            assign = (dense @ cn.T).argmax(axis=1)
            oh = np.zeros((row_block, k), np.float32)
            oh[np.arange(row_block), assign] = valid[sl]
            ext = np.concatenate([dense, np.ones((row_block, 1),
                                                 np.float32)], axis=1)
            stats += oh.T @ ext
        return stats

    host_pass()  # warm caches
    t0 = time.perf_counter()
    host_pass()
    dt_host = time.perf_counter() - t0

    mpts_dev = n / dt_dev / 1e6
    mpts_host = n / dt_host / 1e6
    rabit_tpu.finalize()
    print(json.dumps({
        "metric": "kmeans_stats_throughput",
        "value": round(mpts_dev, 3),
        "unit": "Mpoints/s",
        "vs_baseline": round(mpts_dev / mpts_host, 3),
    }))


if __name__ == "__main__":
    main()
