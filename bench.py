"""Benchmark harness — prints ONE JSON line.

Benchmarks the flagship workload: full k-means iterations (assign +
accumulate + recompute, the per-iteration work of the reference app,
reference: rabit-learn/kmeans/kmeans.cc:121-157).  The framework path is
``kmeans.device_iterations`` — the device-resident chained loop the app
uses via ``kmeans.run(device_chain=...)`` — with the fused Pallas stats
kernel (rabit_tpu/ops/kmeans_kernel.py) or an XLA two-matmul pass,
whichever is faster on the local chip, syncing to the host once per
chain.  The baseline is the reference's design point — host-side compute
feeding the collective — implemented as strong *vectorized* numpy
(already far faster than the reference's actual per-row C++ loop, so
vs_baseline is conservative).

Both sides measure the iteration compute only (no cross-rank allreduce
and no checkpoint on either side; at world=1 the chained path is exactly
what the app executes between checkpoints).

Metric: million points/sec through one full k-means iteration
(k=64 clusters, d=256 features, 512k points densified from 32-nnz rows).
"""
from __future__ import annotations

import json
import time

import numpy as np

N, D, K, NNZ = 1 << 19, 256, 64, 32
ITERS = 50
ROW_BLOCK = 2048
HOST_BLOCK = 8192
assert N % HOST_BLOCK == 0, "host baseline drops remainder rows otherwise"


def main() -> None:
    import jax
    import jax.numpy as jnp

    import rabit_tpu
    from rabit_tpu.learn import kmeans

    rabit_tpu.init(rabit_engine="empty")

    rng = np.random.default_rng(0)
    findex = rng.integers(0, D, (N, NNZ)).astype(np.int32)
    fvalue = rng.standard_normal((N, NNZ)).astype(np.float32)
    cent0 = rng.standard_normal((K, D)).astype(np.float32)

    # densify once on host (scatter is centroid-independent; the app does
    # this staging on device via prepare_shard)
    dense = np.zeros((N, D), np.float32)
    rows = np.arange(N)[:, None]
    np.add.at(dense, (rows, findex), fvalue)
    valid = np.ones(N, np.float32)

    x_dev = jax.device_put(jnp.asarray(dense))
    v_dev = jax.device_put(jnp.asarray(valid))
    c_dev = jax.device_put(jnp.asarray(cent0))

    def timed(use_pallas: bool) -> float:
        # warm/compile the full chained loop, then time a second run
        out = kmeans.device_iterations(c_dev, x_dev, v_dev, ITERS,
                                       use_pallas=use_pallas,
                                       block=ROW_BLOCK)
        np.asarray(out)
        t0 = time.perf_counter()
        out = kmeans.device_iterations(c_dev, x_dev, v_dev, ITERS,
                                       use_pallas=use_pallas,
                                       block=ROW_BLOCK)
        np.asarray(out)  # one host sync for the whole chain
        return (time.perf_counter() - t0) / ITERS

    on_tpu = jax.default_backend() == "tpu"
    dt_xla = timed(use_pallas=False)
    dt_dev = dt_xla
    if on_tpu:
        try:
            dt_dev = min(dt_xla, timed(use_pallas=True))
        except Exception:
            pass

    # host baseline: the reference's design point (CPU compute + CPU
    # reducer, kmeans.cc:126-140), vectorized numpy, one iteration
    def host_pass(model):
        cn = model / np.linalg.norm(model, axis=1, keepdims=True)
        stats = np.zeros((K, D + 1), np.float32)
        for b in range(N // HOST_BLOCK):
            sl = slice(b * HOST_BLOCK, (b + 1) * HOST_BLOCK)
            xb = dense[sl]
            assign = (xb @ cn.T).argmax(axis=1)
            oh = np.zeros((HOST_BLOCK, K), np.float32)
            oh[np.arange(HOST_BLOCK), assign] = 1.0
            ext = np.concatenate([xb, np.ones((HOST_BLOCK, 1), np.float32)],
                                 axis=1)
            stats += oh.T @ ext
        return stats

    host_pass(cent0)  # warm caches
    t0 = time.perf_counter()
    host_pass(cent0)
    dt_host = time.perf_counter() - t0

    mpts_dev = N / dt_dev / 1e6
    mpts_host = N / dt_host / 1e6
    rabit_tpu.finalize()
    print(json.dumps({
        "metric": "kmeans_device_iteration_throughput",
        "value": round(mpts_dev, 3),
        "unit": "Mpoints/s",
        "vs_baseline": round(mpts_dev / mpts_host, 3),
    }))


if __name__ == "__main__":
    main()
