"""Benchmark harness — prints ONE JSON line.

Measures allreduce throughput through the framework's device-resident path
on the available accelerator, mirroring the reference's speed_test sweep
(reference: test/speed_test.cc:53-97).  vs_baseline compares against the
host/numpy loopback path (the reference design's CPU-side reducer), i.e.
the speedup from keeping buffers device-resident.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time(fn, *args, repeats=20):
    jax.block_until_ready(fn(*args))  # warmup / compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeats


def main() -> None:
    n = 1 << 24  # 16M float32 = 64 MB
    x = jnp.ones((n,), dtype=jnp.float32)

    # Device-resident reduction step (single-chip: measures the on-device
    # reduction + no host round-trip; multi-chip: would ride ICI collectives).
    @jax.jit
    def device_reduce(v):
        return v * 2.0  # elementwise op standing in for the reduce combine

    dt_dev = _time(device_reduce, x)

    # Host path: device->host, numpy combine, host->device (reference-style).
    def host_reduce(v):
        h = np.asarray(v)
        h = h * 2.0
        return jnp.asarray(h)

    dt_host = _time(host_reduce, x, repeats=5)

    nbytes = n * 4
    gbps = nbytes / dt_dev / 1e9
    # Placeholder metric until the XLA engine lands: measures the
    # device-resident elementwise path vs the reference-style host
    # round-trip, NOT a real collective yet.
    print(json.dumps({
        "metric": "device_resident_reduce_throughput_placeholder",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dt_host / dt_dev, 3),
    }))


if __name__ == "__main__":
    main()
