// Lazy-prepared allreduce tutorial against the public C++ API: the
// prepare lambda fills the buffer and is skipped when a cached result is
// replayed during recovery.
// TPU-native equivalent of the reference tutorial
// (reference: guide/lazy_allreduce.cc).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/lazy_allreduce_cc
#include <cstdio>

#include "rabit_tpu/rabit_tpu.h"

namespace rt = rabit_tpu;

int main(int argc, char* argv[]) {
  const int kN = 3;
  float a[kN];
  rt::Init(argc - 1, argv + 1);
  int rank = rt::GetRank();
  rt::Allreduce<rt::op::Max>(a, kN, [&] {
    std::printf("@node[%d] run prepare function\n", rank);
    for (int i = 0; i < kN; ++i) a[i] = static_cast<float>(rank + i);
  });
  std::printf("@node[%d] after-allreduce-max: %g %g %g\n", rank, a[0], a[1],
              a[2]);
  rt::Allreduce<rt::op::Sum>(a, kN);
  std::printf("@node[%d] after-allreduce-sum: %g %g %g\n", rank, a[0], a[1],
              a[2]);
  rt::Finalize();
  return 0;
}
