// Lazy-prepared allreduce tutorial against the C ABI: the prepare
// callback fills the buffer and is skipped when a cached result is
// replayed during recovery.
// TPU-native equivalent of the reference tutorial
// (reference: guide/lazy_allreduce.cc).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/lazy_allreduce_cc
#include <cstdio>

#include "rabit_tpu/c_api.h"

static const int kN = 3;
static float a[kN];

static void prepare(void* /*arg*/) {
  printf("@node[%d] run prepare function\n", RbtTpuGetRank());
  for (int i = 0; i < kN; ++i) a[i] = static_cast<float>(RbtTpuGetRank() + i);
}

int main(int argc, char* argv[]) {
  const char** params = const_cast<const char**>(argv + 1);
  if (RbtTpuInit(argc - 1, params) != 0) {
    fprintf(stderr, "init failed: %s\n", RbtTpuGetLastError());
    return 1;
  }
  int rank = RbtTpuGetRank();
  printf("@node[%d] before-allreduce: %g %g %g\n", rank, a[0], a[1], a[2]);
  // dtype 6 = float32, op 0 = max (rabit_tpu/ops/reduce_ops.py)
  RbtTpuAllreduce(a, kN, 6, 0, prepare, nullptr);
  printf("@node[%d] after-allreduce-max: %g %g %g\n", rank, a[0], a[1], a[2]);
  RbtTpuAllreduce(a, kN, 6, 2, nullptr, nullptr);
  printf("@node[%d] after-allreduce-sum: %g %g %g\n", rank, a[0], a[1], a[2]);
  RbtTpuFinalize();
  return 0;
}
