#!/usr/bin/env python
"""Broadcast an arbitrary Python object from rank 0.

TPU-native equivalent of the reference tutorial (reference:
guide/broadcast.py, guide/broadcast.cc).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import rabit_tpu

rabit_tpu.init()
rank = rabit_tpu.get_rank()
s = None
if rank == 0:
    s = {"hello world": 100, 2: 3}
print(f'@node[{rank}] before-broadcast: s="{s}"')
s = rabit_tpu.broadcast(s, 0)
print(f'@node[{rank}] after-broadcast: s="{s}"')
rabit_tpu.finalize()
