#!/usr/bin/env python
"""Lazy-prepared allreduce: the prepare function fills the buffer and is
skipped when a cached result is replayed during failure recovery.

TPU-native equivalent of the reference tutorial (reference:
guide/lazy_allreduce.cc).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import rabit_tpu

rabit_tpu.init()
rank = rabit_tpu.get_rank()
a = np.zeros(3, dtype=np.int32)


def prepare():
    print(f"@node[{rank}] run prepare function")
    for i in range(len(a)):
        a[i] = rank + i


print(f"@node[{rank}] before-allreduce: {a}")
rabit_tpu.allreduce(a, rabit_tpu.MAX, prepare_fun=prepare)
print(f"@node[{rank}] after-allreduce-max: {a}")

rabit_tpu.allreduce(a, rabit_tpu.SUM)
print(f"@node[{rank}] after-allreduce-sum: {a}")
rabit_tpu.finalize()
