// Minimal allreduce/broadcast walkthrough against the public C++ API.
// TPU-native equivalent of the reference tutorial (reference: guide/basic.cc,
// which uses rabit::Allreduce<op::Max>/<op::Sum> and rabit::Broadcast).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/basic_cc
#include <cstdio>
#include <string>

#include "rabit_tpu/rabit_tpu.h"

namespace rt = rabit_tpu;

int main(int argc, char* argv[]) {
  const int kN = 3;
  rt::Init(argc - 1, argv + 1);
  int rank = rt::GetRank();
  float a[kN];
  for (int i = 0; i < kN; ++i) a[i] = static_cast<float>(rank + i);
  std::printf("@node[%d] before-allreduce: %g %g %g\n", rank, a[0], a[1],
              a[2]);
  rt::Allreduce<rt::op::Max>(a, kN);
  std::printf("@node[%d] after-allreduce-max: %g %g %g\n", rank, a[0], a[1],
              a[2]);
  rt::Allreduce<rt::op::Sum>(a, kN);
  std::printf("@node[%d] after-allreduce-sum: %g %g %g\n", rank, a[0], a[1],
              a[2]);

  std::string msg;
  if (rank == 0) msg = "hello from rank 0";
  rt::Broadcast(&msg, 0);
  std::printf("@node[%d] broadcast: %s\n", rank, msg.c_str());
  rt::Finalize();
  return 0;
}
