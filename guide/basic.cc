// Minimal allreduce/broadcast walkthrough against the C ABI.
// TPU-native equivalent of the reference tutorial (reference: guide/basic.cc).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/basic_cc
#include <cstdio>
#include <cstring>

#include "rabit_tpu/c_api.h"

int main(int argc, char* argv[]) {
  const int kN = 3;
  const char** params = const_cast<const char**>(argv + 1);
  if (RbtTpuInit(argc - 1, params) != 0) {
    fprintf(stderr, "init failed: %s\n", RbtTpuGetLastError());
    return 1;
  }
  int rank = RbtTpuGetRank();
  float a[kN];
  for (int i = 0; i < kN; ++i) a[i] = static_cast<float>(rank + i);
  printf("@node[%d] before-allreduce: %g %g %g\n", rank, a[0], a[1], a[2]);
  // dtype 6 = float32, op 0 = max (rabit_tpu/ops/reduce_ops.py)
  RbtTpuAllreduce(a, kN, 6, 0, nullptr, nullptr);
  printf("@node[%d] after-allreduce-max: %g %g %g\n", rank, a[0], a[1], a[2]);
  RbtTpuAllreduce(a, kN, 6, 2, nullptr, nullptr);
  printf("@node[%d] after-allreduce-sum: %g %g %g\n", rank, a[0], a[1], a[2]);

  char msg[64] = {0};
  if (rank == 0) snprintf(msg, sizeof(msg), "hello from rank 0");
  RbtTpuBroadcast(msg, sizeof(msg), 0);
  printf("@node[%d] broadcast: %s\n", rank, msg);
  RbtTpuTrackerPrint("basic.cc done\n");
  RbtTpuFinalize();
  return 0;
}
