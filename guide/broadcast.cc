// Broadcast tutorial against the public C++ API.
// TPU-native equivalent of the reference tutorial
// (reference: guide/broadcast.cc).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/broadcast_cc
#include <cstdio>
#include <string>

#include "rabit_tpu/rabit_tpu.h"

namespace rt = rabit_tpu;

int main(int argc, char* argv[]) {
  rt::Init(argc - 1, argv + 1);
  int rank = rt::GetRank();
  std::string s;
  if (rank == 0) s = "hello world";
  std::printf("@node[%d] before-broadcast: s=\"%s\"\n", rank, s.c_str());
  rt::Broadcast(&s, 0);
  std::printf("@node[%d] after-broadcast: s=\"%s\"\n", rank, s.c_str());
  rt::Finalize();
  return 0;
}
