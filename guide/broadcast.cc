// Broadcast tutorial against the C ABI.
// TPU-native equivalent of the reference tutorial (reference: guide/broadcast.cc).
// Build: make -C guide && run under the launcher:
//   python -m rabit_tpu.tracker.launch_local -n 3 guide/broadcast_cc
#include <cstdio>
#include <cstring>

#include "rabit_tpu/c_api.h"

int main(int argc, char* argv[]) {
  const char** params = const_cast<const char**>(argv + 1);
  if (RbtTpuInit(argc - 1, params) != 0) {
    fprintf(stderr, "init failed: %s\n", RbtTpuGetLastError());
    return 1;
  }
  int rank = RbtTpuGetRank();
  char s[32] = {0};
  if (rank == 0) snprintf(s, sizeof(s), "hello world");
  printf("@node[%d] before-broadcast: s=\"%s\"\n", rank, s);
  RbtTpuBroadcast(s, sizeof(s), 0);
  printf("@node[%d] after-broadcast: s=\"%s\"\n", rank, s);
  RbtTpuFinalize();
  return 0;
}
