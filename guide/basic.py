#!/usr/bin/env python
"""Minimal allreduce/broadcast walkthrough.

TPU-native equivalent of the reference tutorial (reference: guide/basic.py,
guide/basic.cc) — runs standalone in a world of one, or distributed when
launched under a tracker.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import rabit_tpu

rabit_tpu.init()
rank = rabit_tpu.get_rank()
world = rabit_tpu.get_world_size()

a = np.zeros(3, dtype=np.float32)
for i in range(len(a)):
    a[i] = rank + i

print(f"@node[{rank}] before-allreduce: {a}")
rabit_tpu.allreduce(a, rabit_tpu.MAX)
print(f"@node[{rank}] after-allreduce-max: {a}")

rabit_tpu.allreduce(a, rabit_tpu.SUM)
print(f"@node[{rank}] after-allreduce-sum: {a}")

s = {"hello world": 100, "rank": 0} if rank == 0 else None
s = rabit_tpu.broadcast(s, root=0)
print(f"@node[{rank}] broadcast: {s}")

rabit_tpu.tracker_print("basic.py done")
rabit_tpu.finalize()
